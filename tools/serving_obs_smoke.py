#!/usr/bin/env python
"""Serving-SLO observability smoke (ci.sh fast tier, FF_TRACE=1).

Drives the serving observability stack end-to-end on the 8-device CPU
mesh and asserts the three contracts the PR makes:

  1. **Lifecycle tracing** — one generate request with a client-sent
     ``x-ff-trace-id`` produces ONE linked trace: admission, queue
     wait, batch assembly, prefill, per-segment decode, and response
     spans all carry that id, the id is echoed on the response, and
     the Chrome export links the spans with flow events
     (``tools/fftrace.py`` merges the serving dump into its own lane);
  2. **Streaming quantile sketches** — after live traffic, ``/healthz``
     reports non-zero sketch quantiles per (model, bucket), the
     ``ff_request_latency_quantile`` gauges land in ``/metrics``, and a
     deadline-expired request (tiny ``x-ff-timeout-ms``) shows up as an
     SLO violation;
  3. **Serving drift detection** — the measured per-bucket decode
     profile lands keyed 1:1 to the serving audit block's predicted
     entries, and an injected mis-calibrated predicted row produces a
     drift report attributing exactly that bucket to the calibration
     rows its pricing consulted — and marks those rows stale.

Exit code 0 = all three contracts hold.
"""
import json
import os
import socket
import sys
import tempfile
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# the whole point of this smoke: the obs ring must be live before any
# flexflow import
os.environ["FF_TRACE"] = "1"
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if "xla_force_host_platform_device_count" not in \
        os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "") +
                               " --xla_force_host_platform_device_count=8")

BUCKETS = (1, 4)
TRACE_ID = "obssmoke0badc0de"
#: span names one generate request's linked trace must cover —
#: admission (HTTP parse), queue (instance-lock wait), batch (bucket
#: padding), prefill + decode (model spans), per-segment decode
#: (session spans), response (terminal outcome)
LIFECYCLE = ("request.admission", "request.queue", "request.batch",
             "request.decode_segment", "request.response",
             "generate.prefill", "generate.decode")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _post(base, path, doc, headers=None):
    req = urllib.request.Request(base + path,
                                 data=json.dumps(doc).encode())
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        r = urllib.request.urlopen(req, timeout=60)
        return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}"), dict(e.headers)


def _get(base, path):
    return json.loads(urllib.request.urlopen(base + path,
                                             timeout=10).read())


def main() -> int:
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    if len(jax.devices()) < 8:
        print("serving obs smoke: need 8 virtual devices", file=sys.stderr)
        return 1

    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
    from flexflow_tpu.models.nlp import GPTConfig, build_gpt2
    from flexflow_tpu.obs import events as obs_events
    from flexflow_tpu.search.calibration import (CalibrationTable,
                                                 MeshCalibration)
    assert obs_events.enabled(), "FF_TRACE=1 did not enable the ring"

    cfg = FFConfig()
    cfg.only_data_parallel = False
    cfg.search_budget = 60
    ff = FFModel(cfg)
    out = build_gpt2(ff, 8, 32, GPTConfig.tiny())
    ff.compile(SGDOptimizer(0.0), "identity", [], output_tensor=out)

    # -- seeded calibration: provenance rows must carry REAL table keys
    # so the drift verdict has something to mark stale ------------------
    cal_dir = tempfile.mkdtemp(prefix="ffobs_cal_")
    tbl = CalibrationTable(cal_dir)
    tbl.put("cpu", "host_membw", "-", 0, 0, 1e10)
    tbl.put("cpu", "host_dispatch", "-", 0, 0, 2e-5)
    ff._search_cost_model.attach_calibration(
        MeshCalibration(backend="cpu", dispatch_s=2e-5, mem_bw=1e10,
                        table=tbl))

    # -- serving-plan search writes the audit block with per-bucket
    # predicted entries + their calibration provenance ------------------
    from flexflow_tpu.search.serving_plan import optimize_serving_strategy
    plan = optimize_serving_strategy(ff, buckets=BUCKETS, budget=60)
    audit_path = getattr(ff, "_strategy_audit_path", None)
    assert audit_path and os.path.exists(audit_path), \
        "serving search wrote no audit record under FF_TRACE=1"
    with open(audit_path) as f:
        audit = json.load(f)
    for b in BUCKETS:
        calib = audit["serving"]["buckets"][str(b)]["calib"]
        assert calib, f"bucket {b} carries no calibration provenance"
        assert any(r["table"] in ("host_membw", "host_dispatch")
                   and r["key"] for r in calib), calib
    print(f"serving obs smoke: audit at {os.path.basename(audit_path)} "
          f"carries calib provenance for buckets {sorted(plan.buckets)}")

    # -- serve the plan behind the threading front ----------------------
    from flexflow_tpu.serving import (InferenceSession, ModelRepository,
                                      serve_http)
    from flexflow_tpu.serving.session import ServingPlanSession
    serving = ServingPlanSession(
        {b: InferenceSession(ff, [b], decode_segment=4) for b in BUCKETS})
    repo = ModelRepository()
    repo.register("gpt2", serving)
    handle = serve_http(repo, port=_free_port(), block=False, max_batch=4)
    base = f"http://127.0.0.1:{handle.server.server_address[1]}"

    try:
        # -- 1. lifecycle trace: one generate request, one linked trace
        rng = np.random.default_rng(0)
        for rows in (1, 4):
            ids = np.zeros((rows, 32), np.int32)
            ids[:, :6] = rng.integers(1, 200, (rows, 6))
            st, obj, hdrs = _post(
                base, "/v2/models/gpt2/generate",
                {"inputs": [{"name": "input_ids", "shape": [rows, 32],
                             "datatype": "int32",
                             "data": ids.ravel().tolist()}],
                 "parameters": {"prompt_len": 6, "max_new_tokens": 8,
                                "temperature": 0.0}},
                headers={"x-ff-trace-id": TRACE_ID} if rows == 1 else None)
            assert st == 200, (st, obj)
            if rows == 1:
                assert hdrs.get("x-ff-trace-id") == TRACE_ID, hdrs
        snap = obs_events.snapshot()
        spans = [e for e in snap["events"] if e.get("kind") == "span"
                 and (e.get("attrs") or {}).get("trace") == TRACE_ID]
        names = {e["name"] for e in spans}
        missing = [n for n in LIFECYCLE if n not in names]
        assert not missing, f"trace {TRACE_ID} missing spans {missing} " \
                            f"(has {sorted(names)})"
        resp = [e for e in spans if e["name"] == "request.response"]
        assert resp and resp[0]["attrs"].get("outcome") == "ok", resp
        segs = {e["attrs"].get("segment")
                for e in spans if e["name"] == "request.decode_segment"}
        assert segs == {0, 1}, f"expected 2 decode segments, got {segs}"
        print(f"serving obs smoke: linked trace covers "
              f"{len(names)} span kinds across {len(spans)} spans")

        # the Chrome export links the trace's spans with flow events,
        # and fftrace merges the serving dump into its own lane
        from flexflow_tpu.obs.trace_export import dump_serving_trace
        dump = dump_serving_trace()
        assert dump, "serving trace dump failed"
        sys.path.insert(0, os.path.join(REPO, "tools"))
        from fftrace import merge_rank_traces
        merged = merge_rank_traces([dump])
        flows = [e for e in merged["traceEvents"]
                 if e.get("id") == TRACE_ID and e.get("ph") in "stf"]
        assert any(e["ph"] == "s" for e in flows) \
            and any(e["ph"] == "f" for e in flows), \
            f"no flow chain for {TRACE_ID}"
        assert any(ln["role"] == "serving"
                   for ln in merged["otherData"]["lanes"])
        print(f"serving obs smoke: fftrace merged serving lane with "
              f"{len(flows)} flow events for the request")

        # -- 2. sketches + SLO: scheduler traffic, one deadline-expired
        ivec = {"inputs": [{"name": "input_ids", "shape": [1, 32],
                            "datatype": "int32", "data": [1] * 32},
                           {"name": "position_ids", "shape": [1, 32],
                            "datatype": "int32",
                            "data": list(range(32))}]}
        for _ in range(3):
            st, obj, _ = _post(base, "/v2/models/gpt2/infer", ivec)
            assert st == 200, (st, obj)
        st, obj, hdrs = _post(base, "/v2/models/gpt2/infer", ivec,
                              headers={"x-ff-timeout-ms": "0.05"})
        assert st in (503, 504), (st, obj)
        assert hdrs.get("x-ff-trace-id"), "no trace id on shed response"
        h = _get(base, "/healthz")
        lat = h["serving"]["gpt2"]["latency_ms"]
        assert lat["all"]["count"] >= 3 and lat["all"]["p50"] > 0, lat
        assert lat.get("1", {}).get("count", 0) >= 3, lat
        stats = _get(base, "/v2/metrics")["models"]["gpt2"]
        assert stats["slo_violations"] >= 1, stats
        assert stats["expired"] + stats["deadline_rejected"] >= 1, stats
        mtext = urllib.request.urlopen(base + "/metrics",
                                       timeout=10).read().decode()
        assert 'ff_request_latency_quantile{' in mtext, \
            "quantile gauges missing from /metrics"
        assert 'ff_slo_violations_total{' in mtext, \
            "SLO burn counter missing from /metrics"
        print(f"serving obs smoke: sketch quantiles live "
              f"(p50={lat['all']['p50']}ms, "
              f"slo_violations={stats['slo_violations']})")

        # ffstat renders one frame against the live server (stdlib-only
        # tool: no jax import, subprocess is cheap)
        import subprocess
        r = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "ffstat.py"),
             "--url", base, "--once"],
            capture_output=True, text=True, timeout=30)
        assert r.returncode == 0 and "gpt2" in r.stdout, \
            (r.returncode, r.stdout, r.stderr)

        # -- 3. drift: measured lands 1:1; an injected mis-calibrated
        # predicted row is attributed and its table rows marked stale
        measured = serving.measured_profile()
        assert set(measured) == {str(b) for b in BUCKETS}, measured
        # inject: pretend the search predicted a 10000x faster decode
        # step for the largest bucket than reality delivers
        victim = str(max(BUCKETS))
        audit["serving"]["buckets"][victim]["decode_step_s"] /= 1e4
        with open(audit_path, "w") as f:
            json.dump(audit, f)
        from flexflow_tpu.obs.drift import (load_drift_report,
                                            serving_drift_report)
        rpath = serving_drift_report(serving, audit_path=audit_path,
                                     cache_dir=cal_dir)
        assert rpath, "serving drift report not written"
        rep = load_drift_report(rpath)
        assert rep["kind"] == "serving", rep
        hits = [e for e in rep["out_of_band"]
                if e["bucket"] == int(victim)
                and e["component"] == "decode_step_s"]
        assert hits, f"injected row not attributed: {rep['out_of_band']}"
        keys = set(hits[0]["calibration_keys"])
        want = {CalibrationTable.key("cpu", "host_membw"),
                CalibrationTable.key("cpu", "host_dispatch")}
        assert keys & want, (keys, want)
        assert rep["stale_marked"] >= 1, rep
        # fresh instance: the sidecar on disk, not tbl's warm cache
        assert set(CalibrationTable(cal_dir).stale_keys()) & want
        # and the audit now carries the measured side, keyed 1:1
        with open(audit_path) as f:
            audit2 = json.load(f)
        assert set(audit2["serving_measured"]["buckets"]) \
            <= set(audit2["serving"]["buckets"]), audit2.keys()
        print(f"serving obs smoke: drift report attributed bucket "
              f"{victim} to {sorted(keys & want)} "
              f"({rep['stale_marked']} row(s) staled)")
    finally:
        handle.stop()

    print("serving obs smoke: PASS")
    return 0


if __name__ == "__main__":
    sys.exit(main())
