"""On-chip simulator-fidelity A/B (VERDICT r4 item 2).

Round 4's fidelity number (task-sim Spearman 0.25) was measured on the
shared-memory CPU host, where no 8-independent-device model can hold.
This script produces the number that matters: with chip-calibrated
constants (matmul-efficiency microbenchmark + per-op on-device
measurement, the ``simulator.cc:537`` analog), how well do the two
final rankers' predicted step times correlate with MEASURED train-step
times on the real TPU, across a spread of workloads?

Single-chip scope (the tunnel exposes one device): predictions and
measurements are both for the 1-device data-parallel program, so this
isolates exactly the layer the CPU host could not validate — per-op
compute cost + additive/task-graph composition — with no collective
modelling in the loop. Collective constants are separately fitted by
``calibrate_collectives`` whenever >1 device is visible and recorded.

One subprocess per workload (a wedged remote compile must not kill the
sweep); each measures first (tunnel windows are short), then predicts.

Usage:  python examples/tpu_fidelity.py [--steps 10] [--out PATH]
        (CPU smoke: JAX_PLATFORMS=cpu ... --workloads mnist_mlp,dlrm)
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
for p in (REPO, HERE):
    if p not in sys.path:
        sys.path.insert(0, p)

from _stats import spearman as _spearman  # noqa: E402

# honor JAX_PLATFORMS=cpu even when a TPU platform plugin is ambient
# (the plugin ignores the env var; config must be set before client init)
if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")

# (builder key, batch) — single-chip-friendly sizes, diverse op mixes:
# embedding-dominated (dlrm/xdl), matmul-dominated (mlp/candle/bert),
# attention (transformer/bert), conv (alexnet)
WORKLOADS = {
    "mnist_mlp": 64,
    "dlrm": 32,
    "xdl": 32,
    "candle_uno": 16,
    "transformer": 8,
    "bert_tiny": 32,
    "bert_base": 8,
    "alexnet_cifar10": 8,
}


def _build(ff, workload: str, batch: int):
    from flexflow_tpu.models import (BertConfig, build_alexnet_cifar10,
                                     build_bert, build_candle_uno,
                                     build_dlrm, build_transformer,
                                     build_xdl)
    if workload == "mnist_mlp":
        import mnist_mlp
        return mnist_mlp.build(ff, ff.config)
    if workload == "dlrm":
        import dlrm
        return build_dlrm(ff, batch, dlrm.CFG)
    if workload == "xdl":
        import xdl
        return build_xdl(ff, batch, xdl.CFG)
    if workload == "candle_uno":
        import candle_uno
        return build_candle_uno(ff, batch, candle_uno.CFG)
    if workload == "transformer":
        import transformer
        return build_transformer(ff, batch, transformer.CFG)
    if workload == "alexnet_cifar10":
        return build_alexnet_cifar10(ff, batch)
    if workload in ("bert_tiny", "bert_base"):
        bcfg = (BertConfig.tiny() if workload == "bert_tiny"
                else BertConfig.base())
        seq = 64 if workload == "bert_tiny" else 128
        bcfg.max_position = seq
        return build_bert(ff, batch, seq, bcfg)
    raise ValueError(workload)


def _child(workload: str, steps: int) -> int:
    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
    from flexflow_tpu.utils.compilation_cache import enable_compilation_cache
    enable_compilation_cache()
    import jax
    import numpy as np
    from bench import timed_mfu

    cfg = FFConfig()
    cfg.batch_size = WORKLOADS[workload]
    cfg.only_data_parallel = True
    ff = FFModel(cfg)
    out = _build(ff, workload, cfg.batch_size)
    ff.compile(SGDOptimizer(0.01), "sparse_categorical_crossentropy", [],
               output_tensor=out if out is not None else None)
    from flexflow_tpu.search.optimizer import _synth_batch
    batch = _synth_batch(ff)

    # 1) MEASURE first (the tunnel can wedge at any moment)
    sps, mfu, flops, n_chips, dt, sps_std = timed_mfu(ff, batch, steps)
    measured_s = dt / steps

    # 2) PREDICT with chip-calibrated constants
    from flexflow_tpu.search.costmodel import OpCostModel
    from flexflow_tpu.search.tasksim import TaskGraphEvaluator
    from flexflow_tpu.search.unity import (GraphCostEvaluator,
                                           data_parallel_graph)
    cost = OpCostModel(ff.dmesh.spec)
    on_chip = jax.devices()[0].platform != "cpu"
    if on_chip:
        cost.calibrate()
        cost.measure_on_device = True
        cost.measure_budget_s = 90.0
    if ff.dmesh.num_devices > 1:
        cost.calibrate_collectives(ff.dmesh)
    g = data_parallel_graph(
        ff.layers, ff.graph_inputs + getattr(ff, "const_inputs", []),
        [ff._output_tensor], ff.dmesh)
    pred = {}
    for name, ev_cls in (("additive", GraphCostEvaluator),
                         ("tasksim", TaskGraphEvaluator)):
        t0 = time.perf_counter()
        pred[name] = ev_cls(cost, ff.dmesh).graph_cost(g).total
        pred[name + "_eval_s"] = round(time.perf_counter() - t0, 3)
    print("RESULT " + json.dumps({
        "workload": workload, "platform": jax.default_backend(),
        "measured_s": measured_s, "sps_per_chip": round(sps, 2),
        "sps_std": round(sps_std, 2), "mfu": round(mfu, 4),
        "pred_additive_s": pred["additive"],
        "pred_tasksim_s": pred["tasksim"],
        "mxu_eff": round(cost.mxu_eff, 4),
        "coll_bw": cost.coll_bw, "coll_lat": cost.coll_lat,
        "measured_ops": on_chip}), flush=True)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--workload", default="")
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--workloads", default=",".join(WORKLOADS))
    ap.add_argument("--out", default=os.path.join(
        REPO, "bench_results", "r05_ranker_fidelity.json"))
    a = ap.parse_args()
    if a.workload:
        return _child(a.workload, a.steps)
    rows = []
    errors = {}

    def summarize():
        """(Re)write the artifact after every workload — the tunnel can
        wedge mid-sweep, and the pipeline's stage timeout must never
        discard measurements already captured."""
        out = {"rows": rows, "errors": errors,
               "captured": time.strftime("%Y-%m-%d %H:%M:%S"),
               "platform": rows[0]["platform"] if rows else None,
               "scope": ("1-device DP programs: per-op compute cost + "
                         "graph composition fidelity, chip-calibrated "
                         "(simulator.cc:537 analog); collectives not in "
                         "the loop on a 1-device tunnel")}
        if len(rows) >= 3:
            meas = [r["measured_s"] for r in rows]
            for k in ("additive", "tasksim"):
                preds = [r[f"pred_{k}_s"] for r in rows]
                out[f"spearman_{k}"] = round(_spearman(preds, meas), 4)
                ratios = [p / m for p, m in zip(preds, meas)]
                out[f"ratio_{k}"] = {
                    r["workload"]: round(p / r["measured_s"], 3)
                    for r, p in zip(rows, preds)}
                gm = 1.0
                for r_ in ratios:
                    gm *= r_
                gm **= 1.0 / len(ratios)
                out[f"geomean_ratio_{k}"] = round(gm, 3)
        tmp = a.out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(out, f, indent=1)
        os.replace(tmp, a.out)
        return out

    for w in a.workloads.split(","):
        w = w.strip()
        try:
            r = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--workload", w, "--steps", str(a.steps)],
                capture_output=True, text=True, timeout=600, cwd=HERE)
            got = None
            for line in r.stdout.splitlines():
                if line.startswith("RESULT "):
                    got = json.loads(line[len("RESULT "):])
            if got:
                rows.append(got)
            else:
                errors[w] = (f"rc={r.returncode}: " + (
                    r.stderr.strip().splitlines() or ["?"])[-1][:200])
        except subprocess.TimeoutExpired:
            errors[w] = "timeout"
        summarize()
        print(f"{w}: {rows[-1] if rows and rows[-1]['workload'] == w else errors.get(w)}",
              flush=True)
    out = summarize()
    print(json.dumps({k: v for k, v in out.items()
                      if k.startswith(("spearman", "geomean"))}))
    print(f"wrote {a.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
