"""XDL ads model (reference ``examples/cpp/XDL``, osdi22ae xdl.sh):
many embedding tables -> MLP -> softmax. Shrunk tables for portability."""
import numpy as np
from _common import run_example
from flexflow_tpu.models import XDLConfig, build_xdl

CFG = XDLConfig(embedding_size=(10000,) * 4)


def batch(cfg, rng):
    b = {"label": rng.integers(0, 2, size=(cfg.batch_size, 1))
         .astype(np.int32)}
    for i, size in enumerate(CFG.embedding_size):
        b[f"sparse_{i}"] = rng.integers(
            0, size, size=(cfg.batch_size, CFG.embedding_bag_size)
        ).astype(np.int32)
    return b


if __name__ == "__main__":
    run_example("xdl",
                lambda ff, cfg: build_xdl(ff, cfg.batch_size, CFG),
                batch)
