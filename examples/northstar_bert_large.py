"""North-star demonstration (BASELINE.md): Unity-searched BERT-large on a
v5e-32 pod slice vs pure data parallelism.

The target machine is described by ``machine_configs/v5e-32.json`` (4x8
ICI torus, 8 hosts) — the analog of the reference's
``--machine-model-file`` (``machine_config_example``) — and strategies
are scored by the native link-level task-graph simulator (machine model
v1, ``search/tasksim.py`` + ``native/src/ffruntime.cc``), the analog of
``Simulator::simulate_runtime`` (``src/runtime/simulator.cc``). No
multi-chip hardware is needed: a 32-virtual-device CPU mesh stands in
for the pod (same mechanism as ``tests/conftest.py``), exactly how the
reference searches for N-GPU strategies from a simulator-equipped
single process (``graph.cc:2046``).

Usage:
  python examples/northstar_bert_large.py [--budget 16] [--batch 256]
      [--seq 512] [--out bench_results/northstar_v5e32_sim.json]
"""
import argparse
import json
import os
import sys
import time

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "")
    + " --xla_force_host_platform_device_count=32").strip()
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from flexflow_tpu import FFConfig, FFModel  # noqa: E402
from flexflow_tpu.models import BertConfig, build_bert  # noqa: E402
from flexflow_tpu.parallel.machine import DeviceMesh  # noqa: E402
from flexflow_tpu.parallel.topology import load_machine_file  # noqa: E402
from flexflow_tpu.search.costmodel import OpCostModel  # noqa: E402
from flexflow_tpu.search.tasksim import TaskGraphEvaluator  # noqa: E402
from flexflow_tpu.search.unity import (data_parallel_graph,  # noqa: E402
                                       unity_search)
from flexflow_tpu.pcg.graph import Graph  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget", type=int, default=16)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--machine", default=os.path.join(
        REPO, "machine_configs", "v5e-32.json"))
    ap.add_argument("--out", default=os.path.join(
        REPO, "bench_results", "northstar_v5e32_sim.json"))
    a = ap.parse_args()

    spec = load_machine_file(a.machine)
    assert len(jax.devices()) >= spec.num_devices, \
        f"need {spec.num_devices} virtual devices"
    dmesh = DeviceMesh(spec, mesh_shape=spec.ici_shape)
    print(f"machine: {spec.generation} x{spec.num_devices} "
          f"ici={spec.ici_shape} hosts={spec.num_hosts}", flush=True)

    cfg = FFConfig()
    cfg.batch_size = a.batch
    ff = FFModel(cfg)
    bcfg = BertConfig()          # defaults are BERT-large
    bcfg.max_position = a.seq
    out = build_bert(ff, a.batch, a.seq, bcfg)
    n_ops = len(ff.layers)
    print(f"bert-large graph: {n_ops} layers, batch {a.batch}, "
          f"seq {a.seq}", flush=True)

    cost_model = OpCostModel(spec)
    ev = TaskGraphEvaluator(cost_model, dmesh)
    inputs = ff.graph_inputs + getattr(ff, "const_inputs", [])

    dp_g = data_parallel_graph(ff.layers, inputs, [out], dmesh)
    dp_cost = ev.graph_cost(dp_g)
    print(f"data-parallel simulated step: {dp_cost.total * 1e3:.3f} ms "
          f"(compute {dp_cost.compute * 1e3:.3f} xfer "
          f"{dp_cost.xfer * 1e3:.3f} sync {dp_cost.sync * 1e3:.3f})",
          flush=True)

    t0 = time.perf_counter()
    info, strategy, gc, graph = unity_search(
        ff.layers, inputs, [out], dmesh, cost_model,
        budget=a.budget, evaluator_cls=TaskGraphEvaluator)
    best = {"kind": "sharding", "cost": gc.total}
    # pipeline candidates compete on cost exactly as in the product path
    # (optimizer._maybe_pipeline / --enable-pipeline-search)
    from flexflow_tpu.search.pipeline_score import best_pipeline
    cand = best_pipeline(ff.layers, dmesh, cost_model)
    if cand is not None:
        print(f"pipeline candidate: S={cand.n_stages} M="
              f"{cand.n_microbatches} v={cand.n_chunks} tp={cand.tp} "
              f"dp={cand.dp_size} cost {cand.cost * 1e3:.3f} ms",
              flush=True)
        if cand.cost < best["cost"]:
            kind = (f"pipeline_dp{cand.dp_size}xpp{cand.n_stages}"
                    f"_m{cand.n_microbatches}")
            if cand.tp > 1:
                kind += f"_tp{cand.tp}"
            if cand.n_chunks > 1:
                kind += f"_interleaved{cand.n_chunks}"
            best = {"kind": kind, "cost": cand.cost}
    search_s = time.perf_counter() - t0
    speedup = dp_cost.total / max(best["cost"], 1e-12)
    print(f"searched simulated step:      {best['cost'] * 1e3:.3f} ms "
          f"({best['kind']})", flush=True)
    print(f"search time: {search_s:.1f}s (budget {a.budget})", flush=True)
    print(f"SEARCHED vs DATA-PARALLEL: {speedup:.2f}x "
          f"(north star: >= 1.5x)", flush=True)

    doc = {
        "_comment": "Simulated (machine-model-v1 link-level task sim) "
                    "searched-vs-DP step time for BERT-large on the "
                    "v5e-32 description — BASELINE.md north-star config. "
                    "Regenerate: python examples/northstar_bert_large.py",
        "machine": os.path.basename(a.machine),
        "model": "bert-large",
        "batch": a.batch,
        "seq": a.seq,
        "budget": a.budget,
        "n_ops": n_ops,
        "dp_ms": round(dp_cost.total * 1e3, 3),
        "searched_ms": round(best["cost"] * 1e3, 3),
        "winner": best["kind"],
        "speedup": round(speedup, 3),
        "search_time_s": round(search_s, 1),
    }
    os.makedirs(os.path.dirname(a.out), exist_ok=True)
    with open(a.out, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"wrote {a.out}", flush=True)
    return 0 if speedup >= 1.5 else 1   # the north-star gate itself


if __name__ == "__main__":
    sys.exit(main())
