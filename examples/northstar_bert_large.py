"""North-star demonstration (BASELINE.md): Unity-searched BERT-large on a
v5e-32 pod slice vs pure data parallelism — THROUGH THE PRODUCT PATH.

The winner comes from ``FFModel.compile`` with the same flags a user
would pass::

  --budget 8 --enable-pipeline-search --machine-model-version 1 \
  --machine-model-file machine_configs/v5e-32.json

The target machine is described by ``machine_configs/v5e-32.json`` (4x8
ICI torus, 8 hosts) — the analog of the reference's
``--machine-model-file`` (``machine_config_example``) — and strategies
are scored by the native link-level task-graph simulator (machine model
v1, ``search/tasksim.py`` + ``flexflow_tpu/native/src/ffruntime.cc``), the analog of
``Simulator::simulate_runtime`` (``src/runtime/simulator.cc``). No
multi-chip hardware is needed: a 32-virtual-device CPU mesh stands in
for the pod (same mechanism as ``tests/conftest.py``), exactly how the
reference searches for N-GPU strategies from a simulator-equipped
single process (``graph.cc:2046``).

Usage:
  python examples/northstar_bert_large.py [--budget 8] [--batch 64]
      [--seq 512] [--out bench_results/northstar_v5e32_sim.json]
"""
import argparse
import json
import os
import sys
import time

import re as _re

_flags = os.environ.get("XLA_FLAGS", "")
_m = _re.search(r"--xla_force_host_platform_device_count=(\d+)", _flags)
if _m is None or int(_m.group(1)) < 32:
    # keep a LARGER pre-set count (e.g. 64 for the 2-slice machine)
    want = "--xla_force_host_platform_device_count=32"
    _flags = _flags.replace(_m.group(0), want) if _m \
        else (_flags + " " + want).strip()
    os.environ["XLA_FLAGS"] = _flags
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# `python examples/northstar_bert_large.py` puts examples/ (not the
# repo root) on sys.path; make the import work without an installed
# package or PYTHONPATH (same idiom as tpu_fidelity.py)
if REPO not in sys.path:
    sys.path.insert(0, REPO)

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer  # noqa: E402
from flexflow_tpu.models import BertConfig, build_bert  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget", type=int, default=8)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--seq", type=int, default=512)
    ap.add_argument("--machine", default=os.path.join(
        REPO, "machine_configs", "v5e-32.json"))
    ap.add_argument("--out", default=os.path.join(
        REPO, "bench_results", "northstar_v5e32_sim.json"))
    a = ap.parse_args()

    # the EXACT product flag spelling (FFConfig.parse_args) — this run
    # is the same code path as any user invocation
    cfg = FFConfig.parse_args([
        "--batch-size", str(a.batch),
        "--budget", str(a.budget),
        "--enable-pipeline-search",
        "--machine-model-version", "1",
        "--machine-model-file", a.machine,
    ])
    from flexflow_tpu.parallel.topology import load_machine_file
    want = load_machine_file(a.machine).num_devices
    assert len(jax.devices()) >= want, \
        (f"need {want} virtual devices for {a.machine}, have "
         f"{len(jax.devices())} — raise "
         f"--xla_force_host_platform_device_count")

    ff = FFModel(cfg)
    bcfg = BertConfig()          # defaults are BERT-large
    bcfg.max_position = a.seq
    out = build_bert(ff, a.batch, a.seq, bcfg)
    n_ops = len(ff.layers)
    print(f"bert-large graph: {n_ops} layers, batch {a.batch}, "
          f"seq {a.seq}", flush=True)

    t0 = time.perf_counter()
    ff.compile(SGDOptimizer(0.01), "sparse_categorical_crossentropy", [],
               output_tensor=out)
    compile_s = time.perf_counter() - t0
    spec = ff.dmesh.spec
    print(f"machine: {spec.generation} x{spec.num_devices} "
          f"hosts={spec.num_hosts}; compile {compile_s:.1f}s", flush=True)

    pred = getattr(ff, "_search_predicted", None)
    assert pred is not None, "search did not record predicted costs"
    dp_ms = pred["dp_cost_s"] * 1e3
    cand = getattr(ff, "_pipeline_choice", None)
    if ff.executor.pipe is not None and cand is not None:
        kind = (f"pipeline_dp{cand.dp_size}xpp{cand.n_stages}"
                f"_m{cand.n_microbatches}")
        if cand.tp > 1:
            kind += f"_tp{cand.tp}"
        if cand.n_chunks > 1:
            kind += f"_interleaved{cand.n_chunks}"
        searched_ms = cand.cost * 1e3
    else:
        kind = "sharding"
        searched_ms = pred["searched_cost_s"] * 1e3
    speedup = dp_ms / max(searched_ms, 1e-9)
    print(f"data-parallel simulated step: {dp_ms:.3f} ms", flush=True)
    print(f"searched simulated step:      {searched_ms:.3f} ms "
          f"({kind})", flush=True)
    print(f"SEARCHED vs DATA-PARALLEL: {speedup:.2f}x "
          f"(north star: >= 1.5x)", flush=True)

    doc = {
        "_comment": "Simulated (machine-model-v1 link-level task sim) "
                    "searched-vs-DP step time for BERT-large on the "
                    "v5e-32 description, selected by FFModel.compile "
                    "with --enable-pipeline-search (the product path). "
                    "Regenerate: python examples/northstar_bert_large.py",
        "machine": os.path.basename(a.machine),
        "model": "bert-large",
        "batch": a.batch,
        "seq": a.seq,
        "budget": a.budget,
        "n_ops": n_ops,
        "dp_ms": round(dp_ms, 3),
        "searched_ms": round(searched_ms, 3),
        "winner": kind,
        "speedup": round(speedup, 3),
        "via": "FFModel.compile",
        "compile_time_s": round(compile_s, 1),
        # search vs materialization split (ff._compile_phases): on the
        # virtual CPU mesh the replicated-param host copies dominate
        # compile_time_s; on real hardware they are parallel DMA
        "compile_phases": getattr(ff, "_compile_phases", None),
    }
    os.makedirs(os.path.dirname(a.out), exist_ok=True)
    with open(a.out, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"wrote {a.out}", flush=True)
    return 0 if speedup >= 1.5 else 1   # the north-star gate itself


if __name__ == "__main__":
    sys.exit(main())
