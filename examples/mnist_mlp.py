"""MLP hello-world (reference ``examples/python/native/mnist_mlp.py`` /
osdi22ae MLP artifact). Synthetic MNIST-shaped data."""
import numpy as np
from _common import run_example
from flexflow_tpu.models import build_mlp


def build(ff, cfg):
    return build_mlp(ff, cfg.batch_size, in_dim=784,
                     hidden=(512, 512), num_classes=10)


def batch(cfg, rng):
    return {"input": rng.normal(size=(cfg.batch_size, 784))
            .astype(np.float32),
            "label": rng.integers(0, 10, size=(cfg.batch_size, 1))
            .astype(np.int32)}


if __name__ == "__main__":
    run_example("mnist_mlp", build, batch)
