"""Final-ranker fidelity A/B: task-sim vs additive (VERDICT r3 item 3).

Unity's DP prunes with the additive ``GraphCostEvaluator`` and (since
r4) re-ranks the finalists through the native event-driven task
simulator. This script measures which ranker's *prediction* — the
searched-vs-DP cost ratio recorded in ``FFModel._search_predicted`` —
better rank-correlates with the MEASURED searched-vs-DP throughput
ratios from ``osdi22ae_results.json`` across the nine artifact
workloads. Search-only (no training), one subprocess per (workload,
ranker) with ``FF_FINAL_RANKER`` selecting the ranker.

The cross-workload Spearman is a crude proxy (the ranker's real job is
ordering candidate strategies *within* one workload, and the measured
DP-floor guard — not the prediction — gates adoption), but it is the
fidelity signal the reference's trust in ``graph_optimize`` rests on
(simulator.cc:537), so both numbers are recorded side by side.

Caveat (recorded in the artifact): the measured ratios were produced
under the default (task-sim) ranker. Where the additive ranker would
adopt a DIFFERENT finalist, its prediction describes a program that
was never measured, so its correlation conflates ranker fidelity with
strategy mismatch. Re-measuring each ranker's own adoptions would cost
the full multi-hour sweep twice; in practice the two rankers'
predictions (and hence adoptions) differ only marginally on these nine
workloads — see the side-by-side predictions in the artifact.

Usage:  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
            python ranker_fidelity.py
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
EXAMPLES = os.path.dirname(HERE)
REPO = os.path.dirname(EXAMPLES)

# (example module, batch size) — batch sizes match run_all.py so the
# predictions correlate against the measured table apples-to-apples
WORKLOADS = {
    "mnist_mlp": 32,
    "alexnet_cifar10": 8,
    "dlrm": 32,
    "xdl": 32,
    "candle_uno": 16,
    "transformer": 8,
    "bert": 4,
    "inception": 4,
    "resnext50": 4,
}


def _child(workload: str) -> int:
    sys.path.insert(0, EXAMPLES)
    sys.path.insert(0, REPO)
    if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
        import jax
        jax.config.update("jax_platforms", "cpu")
    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer

    import importlib
    m = importlib.import_module(workload)
    from flexflow_tpu.models import (build_alexnet_cifar10,
                                     build_candle_uno, build_dlrm,
                                     build_inception_v3, build_resnext50,
                                     build_transformer, build_xdl)
    builders = {
        "mnist_mlp": lambda ff, cfg: m.build(ff, cfg),
        "alexnet_cifar10":
            lambda ff, cfg: build_alexnet_cifar10(ff, cfg.batch_size),
        "dlrm": lambda ff, cfg: build_dlrm(ff, cfg.batch_size, m.CFG),
        "xdl": lambda ff, cfg: build_xdl(ff, cfg.batch_size, m.CFG),
        "candle_uno":
            lambda ff, cfg: build_candle_uno(ff, cfg.batch_size, m.CFG),
        "transformer":
            lambda ff, cfg: build_transformer(ff, cfg.batch_size, m.CFG),
        "bert": lambda ff, cfg: m.build(ff, cfg),
        "inception": lambda ff, cfg: build_inception_v3(
            ff, cfg.batch_size, image_hw=m.HW),
        "resnext50": lambda ff, cfg: build_resnext50(
            ff, cfg.batch_size, image_hw=m.HW),
    }
    cfg = FFConfig()
    cfg.batch_size = WORKLOADS[workload]
    cfg.only_data_parallel = False
    cfg.search_budget = 8
    cfg.search_floor_guard = "false"
    ff = FFModel(cfg)
    out = builders[workload](ff, cfg)
    ff.compile(SGDOptimizer(0.01), "sparse_categorical_crossentropy", [],
               output_tensor=out if out is not None else None)
    pred = getattr(ff, "_search_predicted", None)
    ratio = (pred["dp_cost_s"] / max(pred["searched_cost_s"], 1e-12)
             if pred else None)
    print("RESULT " + json.dumps({"workload": workload, "ratio": ratio}))
    return 0




def main() -> int:
    if len(sys.argv) > 2 and sys.argv[1] == "--workload":
        return _child(sys.argv[2])
    sys.path.insert(0, HERE)
    from run_all import _spearman
    with open(os.path.join(HERE, "osdi22ae_results.json")) as f:
        measured_doc = json.load(f)
    measured = {}
    for script, e in measured_doc["results"].items():
        if ("searched_vs_dp" in e
                and e.get("floor_guard_adopted") != "dp"):
            measured[script.removesuffix(".py")] = e["searched_vs_dp"]
    out = {"measured": measured, "predictions": {}, "spearman": {},
           "caveat": ("measured ratios were taken under the task-sim "
                      "ranker's adoptions; where the additive ranker "
                      "would adopt differently its prediction describes "
                      "an unmeasured program (see module docstring)")}
    for ranker in ("tasksim", "additive"):
        preds = {}
        for w in WORKLOADS:
            env = dict(os.environ, FF_FINAL_RANKER=ranker)
            err = ""
            try:
                r = subprocess.run(
                    [sys.executable, os.path.abspath(__file__),
                     "--workload", w],
                    capture_output=True, text=True, timeout=1200,
                    env=env, cwd=HERE)
                for line in r.stdout.splitlines():
                    if line.startswith("RESULT "):
                        d = json.loads(line[len("RESULT "):])
                        if d["ratio"] is not None:
                            preds[w] = round(d["ratio"], 4)
                if w not in preds:
                    err = (f"rc={r.returncode}: "
                           + (r.stderr.strip().splitlines() or ["?"])[-1]
                           [:160])
            except subprocess.TimeoutExpired:
                err = "timeout"
            if err:
                out.setdefault("errors", {})[f"{ranker}/{w}"] = err
            print(f"{ranker}/{w}: {preds.get(w, err)}", flush=True)
        out["predictions"][ranker] = preds
        pairs = [(preds[w], measured[w]) for w in preds if w in measured]
        if len(pairs) >= 3:
            out["spearman"][ranker] = round(
                _spearman([p for p, _ in pairs], [m for _, m in pairs]), 4)
            out["n_" + ranker] = len(pairs)
    path = os.environ.get(
        "FF_FIDELITY_OUT",
        os.path.join(REPO, "bench_results", "cpu_ranker_fidelity.json"))
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out["spearman"]))
    print(f"wrote {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
