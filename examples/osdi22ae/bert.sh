#!/bin/bash
# A/B: searched strategy vs --only-data-parallel
# (mirrors reference scripts/osdi22ae/bert.sh methodology)
cd "$(dirname "$0")/.." && python bert.py --ab "$@"
