#!/bin/bash
# A/B: searched strategy vs --only-data-parallel
# (mirrors reference scripts/osdi22ae/resnext-50.sh methodology)
cd "$(dirname "$0")/.." && python resnext50.py --ab "$@"
