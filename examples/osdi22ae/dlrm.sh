#!/bin/bash
# A/B: searched strategy vs --only-data-parallel
# (mirrors reference scripts/osdi22ae/dlrm.sh methodology)
cd "$(dirname "$0")/.." && python dlrm.py --ab "$@"
