"""Run every OSDI'22-artifact A/B (searched strategy vs data parallel)
and record the results as JSON — the reference's ``scripts/osdi22ae/``
produce these numbers by hand; here one command captures them all.

Default platform: whatever jax exposes (real TPU under the driver, or
force the 8-device CPU mesh with ``JAX_PLATFORMS=cpu XLA_FLAGS=
--xla_force_host_platform_device_count=8``). Each model runs in its own
subprocess so one failure cannot take down the sweep.
"""
from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
EXAMPLES = os.path.dirname(HERE)

# (script, extra args) — batch sizes sized for the CPU sim; pass
# --batch-size on the command line to override for a real chip
MODELS = [
    ("mnist_mlp.py", ["-b", "32"]),
    ("alexnet_cifar10.py", ["-b", "8"]),
    ("dlrm.py", ["-b", "32"]),
    ("xdl.py", ["-b", "32"]),
    ("candle_uno.py", ["-b", "16"]),
    ("transformer.py", ["-b", "8"]),
    ("bert.py", ["-b", "4"]),
    ("inception.py", ["-b", "4"]),
    ("resnext50.py", ["-b", "4"]),
]

_LINE = re.compile(r"\[(?P<name>[\w-]+)\] (?P<mode>data-parallel|searched):"
                   r" (?P<sps>[\d.]+) samples/s")
_RATIO = re.compile(r"searched vs data-parallel: (?P<ratio>[\d.]+)x")


def main():
    extra = sys.argv[1:]
    results = {}
    for script, args in MODELS:
        # --floor-guard true: the searched leg times itself against the
        # DP program and falls back when it measures slower, so no A/B
        # row can lose to data parallel by more than timing noise
        cmd = [sys.executable, os.path.join(EXAMPLES, script), "--ab",
               "--budget", "8", "--floor-guard", "true"] + args + extra
        t0 = time.time()
        try:
            r = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=1800, cwd=EXAMPLES)
            out = r.stdout
            entry = {"rc": r.returncode,
                     "wall_s": round(time.time() - t0, 1)}
            for m in _LINE.finditer(out):
                key = "dp_sps" if m.group("mode") == "data-parallel" \
                    else "searched_sps"
                entry[key] = float(m.group("sps"))
            m = _RATIO.search(out)
            if m:
                entry["searched_vs_dp"] = float(m.group("ratio"))
            if r.returncode != 0:
                entry["error"] = (r.stderr.strip().splitlines()
                                  or ["?"])[-1][:200]
        except subprocess.TimeoutExpired:
            entry = {"rc": -1, "error": "timeout",
                     "wall_s": round(time.time() - t0, 1)}
        results[script] = entry
        print(f"{script}: {entry}", flush=True)
    # platform info WITHOUT initializing a backend in this process (the
    # ambient TPU plugin ignores JAX_PLATFORMS and can hang on a dead
    # tunnel); the per-model subprocesses already ran on the right one
    doc = {"jax_platforms_env": os.environ.get("JAX_PLATFORMS", "default"),
           "results": results}
    out_path = os.path.join(HERE, "osdi22ae_results.json")
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
