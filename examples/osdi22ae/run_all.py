"""Run every OSDI'22-artifact A/B (searched strategy vs data parallel)
and record the results as JSON — the reference's ``scripts/osdi22ae/``
produce these numbers by hand; here one command captures them all.

Default platform: whatever jax exposes (real TPU under the driver, or
force the 8-device CPU mesh with ``JAX_PLATFORMS=cpu XLA_FLAGS=
--xla_force_host_platform_device_count=8``). Each model runs in its own
subprocess so one failure cannot take down the sweep.
"""
from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
EXAMPLES = os.path.dirname(HERE)

# (script, extra args) — batch sizes sized for the CPU sim; pass
# --batch-size on the command line to override for a real chip
MODELS = [
    ("mnist_mlp.py", ["-b", "32"]),
    ("alexnet_cifar10.py", ["-b", "8"]),
    ("dlrm.py", ["-b", "32"]),
    ("xdl.py", ["-b", "32"]),
    ("candle_uno.py", ["-b", "16"]),
    ("transformer.py", ["-b", "8"]),
    ("bert.py", ["-b", "4"]),
    ("inception.py", ["-b", "4"]),
    ("resnext50.py", ["-b", "4"]),
]

_LINE = re.compile(r"\[(?P<name>[\w-]+)\] (?P<mode>data-parallel|searched):"
                   r" (?P<sps>[\d.]+) samples/s"
                   r"(?: \(std (?P<std>[\d.]+), n=(?P<n>\d+))?")
_RATIO = re.compile(r"searched vs data-parallel: (?P<ratio>[\d.]+)x")
_PRED = re.compile(r"predicted searched-vs-dp: (?P<ratio>[\d.]+)x")
_GUARD = re.compile(r"floor-guard adopted: (?P<which>\w+)")


if EXAMPLES not in sys.path:
    sys.path.insert(0, EXAMPLES)
# _stats is stdlib-only: the sweep parent must stay importable when the
# framework/jax is broken (failures belong in per-model subprocess rows)
from _stats import spearman as _spearman  # noqa: E402


def main():
    extra = sys.argv[1:]
    results = {}
    for script, args in MODELS:
        # --floor-guard true: the searched leg times itself against the
        # DP program and falls back when it measures slower, so no A/B
        # row can lose to data parallel by more than timing noise.
        # --repeats 3: each leg's steady-state loop is timed three times
        # so every sps row carries a stddev; --min-steps 8 floors the
        # short bert/transformer loops so per-run noise stays bounded
        cmd = [sys.executable, os.path.join(EXAMPLES, script), "--ab",
               "--budget", "8", "--floor-guard", "true",
               "--repeats", "3", "--min-steps", "8"] + args + extra
        t0 = time.time()
        try:
            r = subprocess.run(cmd, capture_output=True, text=True,
                               timeout=3600, cwd=EXAMPLES)
            out = r.stdout
            entry = {"rc": r.returncode,
                     "wall_s": round(time.time() - t0, 1)}
            for m in _LINE.finditer(out):
                key = "dp_sps" if m.group("mode") == "data-parallel" \
                    else "searched_sps"
                entry[key] = float(m.group("sps"))
                if m.group("std") is not None:
                    entry[key + "_std"] = float(m.group("std"))
                    entry[key + "_n"] = int(m.group("n"))
            m = _RATIO.search(out)
            if m:
                entry["searched_vs_dp"] = float(m.group("ratio"))
            # ratio error from per-leg standard errors of the mean
            # (the sps values are means over n runs, so their
            # uncertainty is std/sqrt(n), not the raw run-to-run std)
            if ("searched_vs_dp" in entry and "dp_sps_std" in entry
                    and "searched_sps_std" in entry
                    and entry.get("dp_sps", 0) > 0
                    and entry.get("searched_sps", 0) > 0):
                sem_dp = (entry["dp_sps_std"]
                          / entry.get("dp_sps_n", 1) ** 0.5)
                sem_s = (entry["searched_sps_std"]
                         / entry.get("searched_sps_n", 1) ** 0.5)
                rel = ((sem_dp / entry["dp_sps"]) ** 2
                       + (sem_s / entry["searched_sps"]) ** 2) ** 0.5
                entry["searched_vs_dp_std"] = round(
                    entry["searched_vs_dp"] * rel, 4)
            m = _PRED.search(out)
            if m:
                entry["predicted_searched_vs_dp"] = float(m.group("ratio"))
            m = _GUARD.search(out)
            if m:
                entry["floor_guard_adopted"] = m.group("which")
            if r.returncode != 0:
                entry["error"] = (r.stderr.strip().splitlines()
                                  or ["?"])[-1][:200]
        except subprocess.TimeoutExpired:
            entry = {"rc": -1, "error": "timeout",
                     "wall_s": round(time.time() - t0, 1)}
        results[script] = entry
        print(f"{script}: {entry}", flush=True)
    # platform info WITHOUT initializing a backend in this process (the
    # ambient TPU plugin ignores JAX_PLATFORMS and can hang on a dead
    # tunnel); the per-model subprocesses already ran on the right one
    doc = {"jax_platforms_env": os.environ.get("JAX_PLATFORMS", "default"),
           "results": results}
    # predicted-vs-measured fidelity across workloads: Spearman rank
    # correlation of the cost model's searched/dp prediction against the
    # measured throughput ratio (the reference's trust in graph_optimize
    # rests on exactly this fidelity, simulator.cc:537)
    # guard-rejected rows measure DP-vs-DP, not the predicted strategy —
    # they carry no fidelity signal and would poison the correlation
    pairs = [(e["predicted_searched_vs_dp"], e["searched_vs_dp"])
             for e in results.values()
             if "predicted_searched_vs_dp" in e and "searched_vs_dp" in e
             and e.get("floor_guard_adopted") != "dp"]
    if len(pairs) >= 3:
        doc["predicted_vs_measured_spearman"] = round(
            _spearman([p for p, _ in pairs], [m for _, m in pairs]), 4)
        doc["n_correlated"] = len(pairs)
    out_path = os.path.join(HERE, "osdi22ae_results.json")
    with open(out_path, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"wrote {out_path}")


if __name__ == "__main__":
    main()
