#!/bin/bash
# A/B: searched strategy vs --only-data-parallel
# (mirrors reference scripts/osdi22ae/inception.sh methodology)
cd "$(dirname "$0")/.." && python inception.py --ab "$@"
