#!/bin/bash
# A/B: searched strategy vs --only-data-parallel
# (mirrors reference scripts/osdi22ae/candle_uno.sh methodology)
cd "$(dirname "$0")/.." && python candle_uno.py --ab "$@"
