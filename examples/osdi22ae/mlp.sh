#!/bin/bash
# A/B: searched strategy vs --only-data-parallel
# (mirrors reference scripts/osdi22ae/mlp.sh methodology)
cd "$(dirname "$0")/.." && python mnist_mlp.py --ab "$@"
