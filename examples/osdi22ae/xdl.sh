#!/bin/bash
# A/B: searched strategy vs --only-data-parallel
# (mirrors reference scripts/osdi22ae/xdl.sh methodology)
cd "$(dirname "$0")/.." && python xdl.py --ab "$@"
