"""HF LLaMA checkpoint -> fused flexflow_tpu -> KV-cache serving demo.

The full import-and-serve pipeline the reference's Triton backend
offers for its frameworks, LLaMA-native here:

  1. a transformers ``LlamaForCausalLM`` (tiny random one by default;
     pass --checkpoint for a local pretrained directory),
  2. ``llama_load_hf_state_dict`` maps its weights onto
     ``build_llama(fused_attention=True)`` (GQA-aware),
  3. generation through the KV-cache incremental decoder — greedy,
     sampled (--temperature/--top-k/--top-p), or beam (--beams),
  4. optionally served over the KServe-style HTTP endpoint (--serve).

  python examples/llama_serve_hf.py --beams 4
  python examples/llama_serve_hf.py --serve --port 8000
"""
import argparse
import json
import sys
import urllib.request

import numpy as np

import _common  # noqa: F401  — repo path + JAX_PLATFORMS=cpu honoring
from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.models import LlamaConfig, build_llama
from flexflow_tpu.models.nlp import llama_load_hf_state_dict

BATCH = 2


def load_hf(checkpoint: str, seq: int):
    from transformers import LlamaForCausalLM
    if checkpoint:
        hf = LlamaForCausalLM.from_pretrained(checkpoint)
        c = hf.config
    else:
        from transformers import LlamaConfig as HFLlamaConfig
        import torch
        torch.manual_seed(0)
        c = HFLlamaConfig(vocab_size=256, hidden_size=64,
                          intermediate_size=128, num_hidden_layers=2,
                          num_attention_heads=4, num_key_value_heads=2,
                          max_position_embeddings=seq,
                          tie_word_embeddings=False)
        hf = LlamaForCausalLM(c)
    cfg = LlamaConfig(
        vocab_size=c.vocab_size, hidden_size=c.hidden_size,
        intermediate_size=c.intermediate_size,
        num_layers=c.num_hidden_layers, num_heads=c.num_attention_heads,
        num_kv_heads=(0 if c.num_key_value_heads == c.num_attention_heads
                      else c.num_key_value_heads),
        max_position=seq, rope_theta=getattr(c, "rope_theta", 10000.0),
        rms_eps=c.rms_norm_eps)
    return hf, cfg


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--checkpoint", default="")
    ap.add_argument("--max-new", type=int, default=12)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--top-k", type=int, default=0)
    ap.add_argument("--top-p", type=float, default=1.0)
    ap.add_argument("--beams", type=int, default=1)
    ap.add_argument("--serve", action="store_true")
    ap.add_argument("--oneshot", action="store_true",
                    help="with --serve: self-check the endpoint then "
                         "exit instead of serving until interrupted")
    ap.add_argument("--port", type=int, default=8000)
    a = ap.parse_args()

    plen = 5
    seq = max(32, plen + a.max_new)      # decode buffer must fit
    hf, lc = load_hf(a.checkpoint, seq)
    ffcfg = FFConfig()
    ffcfg.batch_size = BATCH
    ffcfg.only_data_parallel = True
    ff = FFModel(ffcfg)
    out = build_llama(ff, BATCH, seq, lc, fused_attention=True)
    ff.compile(SGDOptimizer(0.0), "sparse_categorical_crossentropy", [],
               output_tensor=out)
    ff.params = llama_load_hf_state_dict(hf.state_dict(), lc, fused=True)
    print(f"imported {lc.num_layers}-layer llama (heads {lc.num_heads}, "
          f"kv {lc.num_kv_heads or lc.num_heads}, vocab {lc.vocab_size})",
          flush=True)

    rng = np.random.default_rng(0)
    ids = np.zeros((BATCH, seq), np.int32)
    ids[:, :plen] = rng.integers(0, lc.vocab_size, (BATCH, plen))
    if a.beams > 1:
        done = np.asarray(ff.generate_beam(ids, plen, a.max_new,
                                           num_beams=a.beams))
    else:
        done = np.asarray(ff.generate(ids, plen, a.max_new,
                                      temperature=a.temperature,
                                      top_k=a.top_k, top_p=a.top_p))
    for r in range(BATCH):
        print(f"row {r}: prompt {ids[r, :plen].tolist()} -> "
              f"{done[r, plen:plen + a.max_new].tolist()}", flush=True)

    if not a.serve:
        return

    from flexflow_tpu.serving import (InferenceSession, ModelRepository,
                                      serve_http)
    repo = ModelRepository()
    repo.register("llama", InferenceSession(ff, batch_buckets=(BATCH,)))
    srv, thread, scheds = serve_http(repo, port=a.port, block=False,
                                     batching=False)
    body = json.dumps({
        "inputs": [{"name": "input_ids", "shape": list(ids.shape),
                    "datatype": "int32",
                    "data": ids.ravel().tolist()}],
        "parameters": {"prompt_len": plen, "max_new_tokens": a.max_new,
                       "num_beams": a.beams, "top_k": a.top_k,
                       "top_p": a.top_p,
                       "temperature": a.temperature}}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{a.port}/v2/models/llama/generate", body,
        {"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as resp:
        doc = json.load(resp)
    served = np.asarray(doc["outputs"][0]["data"]).reshape(
        doc["outputs"][0]["shape"])
    assert (served[:, :plen + a.max_new]
            == done[:, :plen + a.max_new]).all(), "serve != local decode"
    print(f"HTTP /generate matches local decode on port {a.port}",
          flush=True)
    if a.oneshot:
        srv.shutdown()
        return
    print("serving until interrupted (Ctrl-C) ...", flush=True)
    try:
        thread.join()
    except KeyboardInterrupt:
        srv.shutdown()


if __name__ == "__main__":
    main()
