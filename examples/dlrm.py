"""DLRM recommender (reference ``examples/cpp/DLRM``, osdi22ae dlrm.sh;
attribute-parallel embedding tables are the searched win). Table sizes
shrunk from the reference's 1M rows so the example runs anywhere."""
import numpy as np
from _common import run_example
from flexflow_tpu.models import DLRMConfig, build_dlrm

CFG = DLRMConfig(embedding_size=(10000,) * 4)


def batch(cfg, rng):
    b = {"dense_input": rng.normal(
        size=(cfg.batch_size, CFG.mlp_bot[0])).astype(np.float32),
         "label": rng.integers(0, 2, size=(cfg.batch_size, 1))
         .astype(np.int32)}
    for i, size in enumerate(CFG.embedding_size):
        b[f"sparse_{i}"] = rng.integers(
            0, size, size=(cfg.batch_size, CFG.embedding_bag_size)
        ).astype(np.int32)
    return b


if __name__ == "__main__":
    run_example("dlrm",
                lambda ff, cfg: build_dlrm(ff, cfg.batch_size, CFG),
                batch)
