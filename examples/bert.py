"""BERT pretraining-shaped workload (reference osdi22ae bert.sh:
``transformer -b 8 --budget 30``)."""
import numpy as np
from _common import run_example
from flexflow_tpu.models import BertConfig, build_bert

SEQ = 128


def build(ff, cfg):
    b = BertConfig.base()
    b.max_position = SEQ
    return build_bert(ff, cfg.batch_size, SEQ, b)


def batch(cfg, rng):
    return {"input_ids": rng.integers(0, 30522,
                                      size=(cfg.batch_size, SEQ))
            .astype(np.int32),
            "position_ids": np.tile(np.arange(SEQ, dtype=np.int32),
                                    (cfg.batch_size, 1)),
            "label": rng.integers(0, 2, size=(cfg.batch_size, 1))
            .astype(np.int32)}


if __name__ == "__main__":
    run_example("bert", build, batch, steps=10)
