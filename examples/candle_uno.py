"""CANDLE-Uno (reference ``examples/cpp/candle_uno``, osdi22ae
candle_uno.sh): per-feature dense towers -> concat -> deep MLP."""
import dataclasses
import numpy as np
from _common import run_example
from flexflow_tpu.models import CandleConfig, build_candle_uno

# shrunk feature dims so the example runs quickly everywhere
CFG = CandleConfig(
    dense_layers=(256,) * 2, dense_feature_layers=(256,) * 2,
    feature_shapes={"dose": 1, "cell.rnaseq": 256,
                    "drug.descriptors": 256, "drug.fingerprints": 256})


def batch(cfg, rng):
    b = {"label": rng.normal(size=(cfg.batch_size, 1)).astype(np.float32)}
    for name, feat in CFG.input_features.items():
        dim = CFG.feature_shapes[feat]
        b[name] = rng.normal(size=(cfg.batch_size, dim)).astype(np.float32)
    return b


if __name__ == "__main__":
    run_example("candle_uno",
                lambda ff, cfg: build_candle_uno(ff, cfg.batch_size, CFG),
                batch, loss="mean_squared_error", metrics=())
