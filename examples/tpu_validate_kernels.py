"""On-chip validation of the Pallas kernels (run on a real TPU).

The CI tier runs the kernels in Pallas interpret mode on CPU
(`tests/test_kernels.py`); this script is the compiled-on-TPU
counterpart: Mosaic lowering, MXU-precision numerics, and the
counter-based in-kernel dropout running compiled. (The round-4 run of
this script caught two TPU-only bugs CPU CI cannot see: Mosaic's
two-word PRNG seed limit, and a per-tile-seeded mask the
differently-blocked backward could not regenerate.)

Checks (each prints PASS/FAIL, exit code 1 on any failure):
  1. fwd numerics vs the plain-XLA golden, f32 + bf16, causal on/off,
     unpadded (512) and padded (393) sequence lengths;
  2. full vjp (dq/dk/dv) vs jax.grad of the golden;
  3. dropout>0: deterministic under one seed, decorrelated across seeds,
     empirical keep-rate ≈ 1-rate, and vjp matches jax.grad of an
     explicit-masked golden built from the kernel's own keep-mask.
"""
import sys

import numpy as np

import jax
import jax.numpy as jnp

from flexflow_tpu.kernels import flash_attention, mha_reference

FAILED = []


def check(name, ok, detail=""):
    print(f"{'PASS' if ok else 'FAIL'} {name} {detail}", flush=True)
    if not ok:
        FAILED.append(name)


def rel_err(a, b):
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    return float(np.abs(a - b).max() / (np.abs(b).max() + 1e-9))


def main():
    from flexflow_tpu.utils.compilation_cache import enable_compilation_cache
    enable_compilation_cache()
    backend = jax.default_backend()
    print(f"backend={backend} devices={jax.devices()}", flush=True)
    if backend != "tpu":
        print("not a TPU — this script validates the compiled path only")
        return 2

    rng = np.random.default_rng(0)

    # MXU default precision is a SINGLE bf16 pass even for fp32 inputs —
    # both the Pallas kernel and the XLA oracle round their matmul
    # operands to bf16, but with different accumulation orders (online
    # softmax vs one-shot), so fp32-on-TPU agreement is bounded by bf16
    # rounding (~1e-2), not fp32 eps. Measured r4 on v5e: fwd <=3.1e-3,
    # bwd <=7.8e-3. The diagnostic below quantifies the hardware
    # rounding itself: oracle@default vs oracle@HIGHEST (3-pass fp32).
    b0, h0, s0, d0 = 2, 4, 512, 64
    qd = jnp.asarray(rng.normal(size=(b0, h0, s0, d0)), jnp.float32)
    kd = jnp.asarray(rng.normal(size=(b0, h0, s0, d0)), jnp.float32)
    vd = jnp.asarray(rng.normal(size=(b0, h0, s0, d0)), jnp.float32)
    o_def = mha_reference(qd, kd, vd)
    o_hi = mha_reference(qd, kd, vd, precision=jax.lax.Precision.HIGHEST)
    mxu_rel = rel_err(o_def, o_hi)
    print(f"INFO mxu default-vs-HIGHEST oracle rel={mxu_rel:.2e} "
          f"(fp32 tolerance floor on this hardware)", flush=True)

    # -- 1/2: numerics + grads ------------------------------------------
    # f32 covers the padded-seq case too; bf16 covers block-aligned only
    # (each (dtype, causal, seq) combo is ~2 remote compiles — keep it lean)
    for dtype, tol_f, tol_g, seqs in (
            (jnp.float32, 1e-2, 2e-2, (512, 393)),
            (jnp.bfloat16, 2e-2, 4e-2, (512,))):
        for causal in (False, True):
            for seq in seqs:
                b, h, d = 2, 4, 64
                q = jnp.asarray(rng.normal(size=(b, h, seq, d)), dtype)
                k = jnp.asarray(rng.normal(size=(b, h, seq, d)), dtype)
                v = jnp.asarray(rng.normal(size=(b, h, seq, d)), dtype)
                tag = f"{dtype.__name__}/causal={causal}/seq={seq}"

                o = flash_attention(q, k, v, causal=causal)
                o_ref = mha_reference(q, k, v, causal=causal)
                check(f"fwd {tag}", rel_err(o, o_ref) < tol_f,
                      f"rel={rel_err(o, o_ref):.2e}")

                def loss(f, a, b_, c):
                    return jnp.sum(
                        f(a, b_, c, causal=causal).astype(jnp.float32) ** 2)

                g = jax.grad(lambda *x: loss(flash_attention, *x),
                             argnums=(0, 1, 2))(q, k, v)
                g_ref = jax.grad(lambda *x: loss(mha_reference, *x),
                                 argnums=(0, 1, 2))(q, k, v)
                worst = max(rel_err(a, b_) for a, b_ in zip(g, g_ref))
                check(f"bwd {tag}", worst < tol_g, f"rel={worst:.2e}")

    # -- 3: in-kernel dropout (TPU-only path) ---------------------------
    b, h, seq, d = 2, 4, 256, 64
    rate = 0.2
    q = jnp.asarray(rng.normal(size=(b, h, seq, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h, seq, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h, seq, d)), jnp.float32)

    o1 = flash_attention(q, k, v, dropout_rate=rate, dropout_seed=7)
    o2 = flash_attention(q, k, v, dropout_rate=rate, dropout_seed=7)
    check("dropout deterministic (same seed)",
          bool(jnp.array_equal(o1, o2)))
    o3 = flash_attention(q, k, v, dropout_rate=rate, dropout_seed=8)
    check("dropout varies across seeds",
          not bool(jnp.array_equal(o1, o3)))

    # keep-rate: with v = all-ones columns the output row is
    # sum(keep*p/(1-r))/sum(p); its mean over rows ≈ 1
    ones_v = jnp.ones_like(v)
    od = flash_attention(q, k, ones_v, dropout_rate=rate, dropout_seed=3)
    mean_keep = float(jnp.mean(od))
    check("dropout keep-rate ~ E=1", abs(mean_keep - 1.0) < 0.05,
          f"mean={mean_keep:.4f}")

    # vjp consistency: the keep mask is a pure position hash, so the
    # exact mask is computable in plain XLA (dropout_keep_mask) and the
    # kernel's grads can be checked against jax.grad of an explicit-
    # masked golden. (Finite differences are useless here: MXU default
    # precision rounds inputs to bf16, whose ~8e-3 resolution swallows
    # an eps-sized perturbation — measured rel ~1 in the r4 runs even
    # though compiled-vs-interpret grads agreed to 1e-4. fp32 fd runs
    # in CPU CI: tests/test_kernels.py.)
    from flexflow_tpu.kernels import dropout_keep_mask

    def golden(qv, kv, vv):
        import math as _m
        sc = 1.0 / _m.sqrt(d)
        s = (jnp.einsum("bhqd,bhkd->bhqk", qv, kv,
                        precision=jax.lax.Precision.HIGHEST)
             .astype(jnp.float32) * sc)
        p = jax.nn.softmax(s, axis=-1)
        keep = dropout_keep_mask(b, h, seq, seq, rate, 11)
        p_eff = jnp.where(keep, p / (1.0 - rate), 0.0)
        return jnp.einsum("bhqk,bhkd->bhqd", p_eff, vv,
                          precision=jax.lax.Precision.HIGHEST)

    probe = jnp.asarray(rng.normal(size=(b, h, seq, d)), jnp.float32)

    def loss_k(*x):
        return jnp.sum(flash_attention(
            *x, dropout_rate=rate, dropout_seed=11).astype(jnp.float32)
            * probe)

    def loss_g(*x):
        return jnp.sum(golden(*x) * probe)

    o_k = flash_attention(q, k, v, dropout_rate=rate, dropout_seed=11)
    rel = rel_err(o_k, golden(q, k, v))
    check("dropout fwd vs explicit-mask golden", rel < 1e-2,
          f"rel={rel:.2e}")
    g_k = jax.grad(loss_k, argnums=(0, 1, 2))(q, k, v)
    g_g = jax.grad(loss_g, argnums=(0, 1, 2))(q, k, v)
    worst = max(rel_err(a, b_) for a, b_ in zip(g_k, g_g))
    check("dropout vjp vs explicit-mask golden", worst < 2e-2,
          f"rel={worst:.2e}")

    print(f"\n{len(FAILED)} failures" if FAILED else "\nALL PASS")
    return 1 if FAILED else 0


if __name__ == "__main__":
    sys.exit(main())
