"""On-chip validation of the Pallas kernels (run on a real TPU).

The CI tier runs the kernels in Pallas interpret mode on CPU
(`tests/test_kernels.py`); this script is the compiled-on-TPU
counterpart the driver environment can actually execute, covering the
TPU-only path as well: in-kernel regenerated dropout
(`flexflow_tpu/kernels/flash_attention.py` — pltpu PRNG has no
interpret-mode lowering, so dropout_rate > 0 can ONLY run here).

Checks (each prints PASS/FAIL, exit code 1 on any failure):
  1. fwd numerics vs the plain-XLA golden, f32 + bf16, causal on/off,
     unpadded (512) and padded (393) sequence lengths;
  2. full vjp (dq/dk/dv) vs jax.grad of the golden;
  3. dropout>0: deterministic under one seed, decorrelated across seeds,
     empirical keep-rate ≈ 1-rate, and vjp matches jax.grad of an
     explicit-masked golden built from the kernel's own keep-mask.
"""
import sys

import numpy as np

import jax
import jax.numpy as jnp

from flexflow_tpu.kernels import flash_attention, mha_reference

FAILED = []


def check(name, ok, detail=""):
    print(f"{'PASS' if ok else 'FAIL'} {name} {detail}", flush=True)
    if not ok:
        FAILED.append(name)


def rel_err(a, b):
    a = np.asarray(a, np.float32)
    b = np.asarray(b, np.float32)
    return float(np.abs(a - b).max() / (np.abs(b).max() + 1e-9))


def main():
    from flexflow_tpu.utils.compilation_cache import enable_compilation_cache
    enable_compilation_cache()
    backend = jax.default_backend()
    print(f"backend={backend} devices={jax.devices()}", flush=True)
    if backend != "tpu":
        print("not a TPU — this script validates the compiled path only")
        return 2

    rng = np.random.default_rng(0)

    # -- 1/2: numerics + grads ------------------------------------------
    # f32 covers the padded-seq case too; bf16 covers block-aligned only
    # (each (dtype, causal, seq) combo is ~2 remote compiles — keep it lean)
    for dtype, tol_f, tol_g, seqs in (
            (jnp.float32, 2e-5, 2e-4, (512, 393)),
            (jnp.bfloat16, 2e-2, 4e-2, (512,))):
        for causal in (False, True):
            for seq in seqs:
                b, h, d = 2, 4, 64
                q = jnp.asarray(rng.normal(size=(b, h, seq, d)), dtype)
                k = jnp.asarray(rng.normal(size=(b, h, seq, d)), dtype)
                v = jnp.asarray(rng.normal(size=(b, h, seq, d)), dtype)
                tag = f"{dtype.__name__}/causal={causal}/seq={seq}"

                o = flash_attention(q, k, v, causal=causal)
                o_ref = mha_reference(q, k, v, causal=causal)
                check(f"fwd {tag}", rel_err(o, o_ref) < tol_f,
                      f"rel={rel_err(o, o_ref):.2e}")

                def loss(f, a, b_, c):
                    return jnp.sum(
                        f(a, b_, c, causal=causal).astype(jnp.float32) ** 2)

                g = jax.grad(lambda *x: loss(flash_attention, *x),
                             argnums=(0, 1, 2))(q, k, v)
                g_ref = jax.grad(lambda *x: loss(mha_reference, *x),
                                 argnums=(0, 1, 2))(q, k, v)
                worst = max(rel_err(a, b_) for a, b_ in zip(g, g_ref))
                check(f"bwd {tag}", worst < tol_g, f"rel={worst:.2e}")

    # -- 3: in-kernel dropout (TPU-only path) ---------------------------
    b, h, seq, d = 2, 4, 256, 64
    rate = 0.2
    q = jnp.asarray(rng.normal(size=(b, h, seq, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, h, seq, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, h, seq, d)), jnp.float32)

    o1 = flash_attention(q, k, v, dropout_rate=rate, dropout_seed=7)
    o2 = flash_attention(q, k, v, dropout_rate=rate, dropout_seed=7)
    check("dropout deterministic (same seed)",
          bool(jnp.array_equal(o1, o2)))
    o3 = flash_attention(q, k, v, dropout_rate=rate, dropout_seed=8)
    check("dropout varies across seeds",
          not bool(jnp.array_equal(o1, o3)))

    # keep-rate: with v = all-ones columns the output row is
    # sum(keep*p/(1-r))/sum(p); its mean over rows ≈ 1
    ones_v = jnp.ones_like(v)
    od = flash_attention(q, k, ones_v, dropout_rate=rate, dropout_seed=3)
    mean_keep = float(jnp.mean(od))
    check("dropout keep-rate ~ E=1", abs(mean_keep - 1.0) < 0.05,
          f"mean={mean_keep:.4f}")

    # vjp consistency: recover the kernel's keep mask by probing each
    # attention with identity-ish tricks is overkill — instead verify the
    # custom vjp against finite differences of the kernel itself.
    def f_scalar(qv):
        o = flash_attention(qv, k, v, dropout_rate=rate, dropout_seed=11)
        return jnp.sum(o.astype(jnp.float32) * probe)

    probe = jnp.asarray(rng.normal(size=(b, h, seq, d)), jnp.float32)
    g = jax.grad(f_scalar)(q)
    eps = 1e-2
    u = jnp.asarray(rng.normal(size=q.shape), jnp.float32)
    u = u / jnp.linalg.norm(u.reshape(-1))
    fd = (f_scalar(q + eps * u) - f_scalar(q - eps * u)) / (2 * eps)
    an = jnp.sum(g * u)
    rel = abs(float(fd - an)) / (abs(float(fd)) + 1e-6)
    check("dropout vjp vs finite-diff", rel < 2e-2, f"rel={rel:.2e}")

    print(f"\n{len(FAILED)} failures" if FAILED else "\nALL PASS")
    return 1 if FAILED else 0


if __name__ == "__main__":
    sys.exit(main())
