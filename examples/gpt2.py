"""GPT-2 causal LM (BASELINE config 5; pipeline/TP target)."""
import numpy as np
from _common import run_example
from flexflow_tpu.models import GPTConfig, build_gpt2

SEQ = 128


def build(ff, cfg):
    g = GPTConfig(hidden_size=256, num_layers=4, num_heads=8,
                  max_position=SEQ)
    return build_gpt2(ff, cfg.batch_size, SEQ, g)


def batch(cfg, rng):
    ids = rng.integers(0, 50257, size=(cfg.batch_size, SEQ))
    return {"input_ids": ids.astype(np.int32),
            "position_ids": np.tile(np.arange(SEQ, dtype=np.int32),
                                    (cfg.batch_size, 1)),
            "label": ids.astype(np.int32)}


if __name__ == "__main__":
    run_example("gpt2", build, batch, steps=5)
