"""On-chip MFU investigation for the flagship BERT-base train step.

Captures (a) a jax.profiler trace of the hot loop (where do the
non-matmul cycles go) and (b) an MFU sweep over the levers VERDICT r2
identified: bf16 activations end-to-end, flash attention on/off, and
batch size. One JSON line per config; summary written to
``bench_results/r03_profile.json``.

Run on the chip (takes ~10-20 min cold, fast with a warm compile cache):
  python examples/tpu_profile_bert.py [--configs base,bf16act,...]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)

# honor JAX_PLATFORMS=cpu even when a TPU platform plugin is ambient
# (the plugin ignores the env var and can hang on a dead tunnel)
if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def _sync(x):
    return float(np.asarray(x))


CONFIGS = {
    # name -> (flash, bf16_activations, batch, seq)
    "tiny":       ("auto",  False, 8, 32),    # CPU smoke of the harness
    "base":       ("auto",  False, 16, 128),
    "bf16act":    ("auto",  True,  16, 128),
    "flash_on":   ("true",  False, 16, 128),
    "flash_off":  ("false", False, 16, 128),
    "b32":        ("auto",  False, 32, 128),
    "b32_bf16":   ("auto",  True,  32, 128),
    "b64_bf16":   ("auto",  True,  64, 128),
    "seq512_flash": ("true", True, 8, 512),
}


def run_config(name, flash, bf16_act, batch, seq, steps, trace_dir=None):
    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
    from flexflow_tpu.models import BertConfig, build_bert
    from flexflow_tpu.parallel.machine import MachineSpec
    from bench import timed_mfu

    cfg = FFConfig()
    cfg.batch_size = batch
    cfg.only_data_parallel = True
    cfg.use_flash_attention = flash
    cfg.bf16_activations = bf16_act
    ff = FFModel(cfg)
    bcfg = BertConfig.tiny() if name == "tiny" else BertConfig.base()
    bcfg.max_position = seq
    bcfg.dropout = 0.1
    out = build_bert(ff, batch, seq, bcfg)
    ff.compile(SGDOptimizer(0.01), "sparse_categorical_crossentropy", [],
               output_tensor=out)
    rng = np.random.default_rng(0)
    b = {"input_ids": rng.integers(0, bcfg.vocab_size,
                                   size=(batch, seq)).astype(np.int32),
         "position_ids": np.tile(np.arange(seq, dtype=np.int32),
                                 (batch, 1)),
         "label": rng.integers(0, 2, size=(batch, 1)).astype(np.int32)}
    if trace_dir:
        # warm the compile first so the trace captures steady-state steps
        step = ff.executor.make_train_step()
        for _ in range(2):
            bm = ff._run_train_step(step, b)
        _sync(bm["loss"])
        import jax.profiler
        with jax.profiler.trace(trace_dir):
            for _ in range(3):
                bm = ff._run_train_step(step, b)
            _sync(bm["loss"])
    # shared bench harness: per-chip sps + MFU, same conventions as
    # BENCH_r* records
    sps, mfu, flops, n_chips, dt, sps_std = timed_mfu(ff, b, steps)
    spec = MachineSpec.detect()
    rec = {"config": name, "flash": flash, "bf16_act": bf16_act,
           "batch": batch, "seq": seq, "steps": steps, "n_chips": n_chips,
           "sps_per_chip": round(sps, 2),
           "sps_std": round(sps_std, 2),
           "ms_per_step": round(dt / steps * 1e3, 3),
           "mfu": round(mfu, 4), "generation": spec.generation}
    print(json.dumps(rec), flush=True)
    return rec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", default=",".join(CONFIGS))
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--trace", default="",
                    help="config name to capture a profiler trace for")
    ap.add_argument("--out", default=os.path.join(
        REPO, "bench_results", "r03_profile.json"))
    a = ap.parse_args()
    from flexflow_tpu.utils.compilation_cache import enable_compilation_cache
    enable_compilation_cache()
    import jax
    print(f"platform: {jax.default_backend()} {jax.devices()}", flush=True)
    results = []
    for name in a.configs.split(","):
        flash, bf16_act, batch, seq = CONFIGS[name.strip()]
        trace_dir = None
        if a.trace and a.trace == name:
            trace_dir = os.path.join(REPO, "bench_results",
                                     f"trace_{name}")
        try:
            results.append(run_config(name, flash, bf16_act, batch, seq,
                                      a.steps, trace_dir))
        except Exception as e:  # noqa: BLE001 — continue the sweep
            results.append({"config": name, "error": repr(e)[:300]})
            print(json.dumps(results[-1]), flush=True)
    doc = {"platform": jax.default_backend(),
           "captured": time.strftime("%Y-%m-%d %H:%M:%S"),
           "results": results}
    os.makedirs(os.path.dirname(a.out), exist_ok=True)
    with open(a.out, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"wrote {a.out}", flush=True)


if __name__ == "__main__":
    main()
