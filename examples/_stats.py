"""Dependency-free stats helpers shared by the example orchestrators.

Deliberately imports nothing beyond the stdlib: the sweep parents
(osdi22ae/run_all.py, tpu_fidelity.py) isolate framework/jax failures in
per-model subprocesses, so the parent must stay importable even when the
framework (or the ambient TPU plugin) is broken.
"""
from __future__ import annotations


def spearman(xs, ys):
    """Spearman rank correlation without scipy (tie-averaged ranks).
    Single shared implementation — the osdi22ae sweep, the ranker
    fidelity A/B and the on-chip fidelity script must stay comparable."""
    def ranks(v):
        order = sorted(range(len(v)), key=lambda i: v[i])
        r = [0.0] * len(v)
        k = 0
        while k < len(order):
            j = k
            while j + 1 < len(order) and v[order[j + 1]] == v[order[k]]:
                j += 1
            avg = (k + j) / 2.0          # averaged rank for ties
            for t in order[k:j + 1]:
                r[t] = avg
            k = j + 1
        return r
    rx, ry = ranks(xs), ranks(ys)
    n = len(xs)
    mx, my = sum(rx) / n, sum(ry) / n
    num = sum((a - mx) * (b - my) for a, b in zip(rx, ry))
    dx = sum((a - mx) ** 2 for a in rx) ** 0.5
    dy = sum((b - my) ** 2 for b in ry) ** 0.5
    return num / (dx * dy) if dx > 0 and dy > 0 else 0.0
