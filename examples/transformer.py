"""Encoder stack (reference ``examples/cpp/Transformer/transformer.cc``)."""
import numpy as np
from _common import run_example
from flexflow_tpu.models import TransformerConfig, build_transformer

CFG = TransformerConfig(num_layers=2, sequence_length=64)


def batch(cfg, rng):
    return {"input": rng.normal(
        size=(cfg.batch_size, CFG.sequence_length, CFG.hidden_size))
        .astype(np.float32),
        "label": rng.normal(size=(cfg.batch_size, CFG.sequence_length, 1))
        .astype(np.float32)}


if __name__ == "__main__":
    run_example("transformer",
                lambda ff, cfg: build_transformer(ff, cfg.batch_size, CFG),
                batch, loss="mean_squared_error", metrics=(), steps=10)
