"""Train a small causal LM, generate from it, and (optionally) serve it.

Demonstrates the decode path end-to-end (the reference has no generation:
its Triton backend serves fixed forwards only):

  python examples/generate_lm.py                 # train + greedy decode
  python examples/generate_lm.py --temperature 0.8 --serve

With --serve, the model is registered in a ModelRepository and decoded
through the KServe-style HTTP endpoint (POST /v2/models/lm/generate).
"""
import argparse
import json
import sys
import urllib.request

import numpy as np

import _common  # noqa: F401  — repo path + JAX_PLATFORMS=cpu honoring
from flexflow_tpu import FFConfig, FFModel, AdamOptimizer
from flexflow_tpu.models import GPTConfig, build_gpt2

BATCH, SEQ = 8, 32


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument("--temperature", type=float, default=0.0)
    ap.add_argument("--max-new", type=int, default=12)
    ap.add_argument("--serve", action="store_true")
    a, rest = ap.parse_known_args()
    cfg = FFConfig.parse_args(rest)
    cfg.batch_size = BATCH
    cfg.only_data_parallel = True

    # toy corpus: arithmetic-progression token sequences the LM can learn
    g = GPTConfig(vocab_size=64, hidden_size=64, num_layers=2,
                  num_heads=4, max_position=SEQ, dropout=0.0)
    ff = FFModel(cfg)
    out = build_gpt2(ff, BATCH, SEQ, g)
    ff.compile(AdamOptimizer(1e-2), "sparse_categorical_crossentropy", [],
               output_tensor=out)

    rng = np.random.default_rng(0)
    pos = np.tile(np.arange(SEQ, dtype=np.int32), (BATCH, 1))
    step = ff.executor.make_train_step()
    import time
    t0 = time.perf_counter()
    for i in range(a.steps):
        start = rng.integers(0, 16, size=(BATCH, 1))
        strd = rng.integers(1, 3, size=(BATCH, 1))
        ids = ((start + strd * np.arange(SEQ)) % g.vocab_size
               ).astype(np.int32)
        # next-token objective: position t is supervised by token t+1
        bm = ff._run_train_step(step, {"input_ids": ids,
                                       "position_ids": pos,
                                       "label": np.roll(ids, -1, axis=1)})
        if i % 10 == 0:
            print(f"step {i}: loss {float(np.asarray(bm['loss'])):.4f}",
                  flush=True)

    dt = time.perf_counter() - t0
    print(f"[generate_lm] train: {BATCH * a.steps / dt:.1f} samples/s")

    prompt = np.zeros((1, SEQ), np.int32)
    prompt[0, :4] = [3, 5, 7, 9]            # stride-2 progression
    got = np.asarray(ff.generate(prompt, prompt_len=4,
                                 max_new_tokens=a.max_new,
                                 temperature=a.temperature))
    print("prompt  :", prompt[0, :4].tolist())
    print("decoded :", got[0, 4:4 + a.max_new].tolist())

    if a.serve:
        import socket
        from flexflow_tpu.serving import (InferenceSession,
                                          ModelRepository, serve_http)
        repo = ModelRepository()
        repo.register("lm", InferenceSession(ff, batch_buckets=(1, 8)))
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        srv, thread, scheds = serve_http(repo, port=port, block=False,
                                         batching=False)
        body = json.dumps({
            "inputs": [{"name": "input_ids", "shape": [1, SEQ],
                        "datatype": "int32",
                        "data": prompt.ravel().tolist()}],
            "parameters": {"prompt_len": 4, "max_new_tokens": a.max_new,
                           "temperature": a.temperature},
        }).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/v2/models/lm/generate", data=body,
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as r:
            served = json.load(r)["outputs"][0]
        srv.shutdown()
        ids = np.asarray(served["data"], np.int32).reshape(1, SEQ)
        print("served  :", ids[0, 4:4 + a.max_new].tolist())
        assert (ids == got).all() or a.temperature > 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
