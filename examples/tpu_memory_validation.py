"""Memory-search validation against XLA's compiled memory numbers
(VERDICT r4 item 7; reference ``graph.cc:1883-1983``).

Two stages, each in its own subprocess:

  A. **estimate vs compiled** (ambient platform — TPU when run from the
     capture pipeline): for each workload, compile the 1-device DP
     program, record the search evaluator's per-device peak-memory
     estimate next to ``utils.debug.compiled_memory_stats`` (XLA's
     argument/output/temp sizes for the actual executable). The
     estimate models params x4 (param+grad+2 moments) + activations, so
     it should land within a small factor of argument+temp+output.

  B. **constrained search binds** (forced CPU 8-virtual-device mesh —
     the 1-device tunnel has no sharding choices): run the memory-aware
     lambda search under a ``--device-mem-mb`` budget set below the
     unconstrained winner's estimate; assert the constrained winner's
     estimate fits the budget and its compiled per-device memory
     dropped vs the unconstrained winner's.

Usage:  python examples/tpu_memory_validation.py [--out PATH]
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
for p in (REPO, HERE):
    if p not in sys.path:
        sys.path.insert(0, p)

# honor JAX_PLATFORMS=cpu even when a TPU platform plugin is ambient
# (the plugin ignores the env var; config must be set before client init)
if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")

ESTIMATE_WORKLOADS = ("bert_tiny", "candle_uno")


def _build_model(workload: str, only_dp: bool, mem_mb: int = 0,
                 batch: int = 16, builder=None, machine_file: str = ""):
    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
    if builder is None:
        from tpu_fidelity import _build as builder
    cfg = FFConfig()
    cfg.batch_size = batch
    cfg.only_data_parallel = only_dp
    cfg.search_floor_guard = "false"
    cfg.machine_model_file = machine_file
    if not only_dp:
        cfg.search_budget = 8
        if mem_mb > 0:
            cfg.enable_memory_search = True
            cfg.device_mem_mb = mem_mb
    ff = FFModel(cfg)
    out = builder(ff, workload, batch)
    ff.compile(SGDOptimizer(0.01), "sparse_categorical_crossentropy", [],
               output_tensor=out if out is not None else None)
    return ff


def _estimate_child(workload: str) -> int:
    import jax
    from flexflow_tpu.search.costmodel import OpCostModel
    from flexflow_tpu.search.unity import (GraphCostEvaluator,
                                           data_parallel_graph)
    from flexflow_tpu.utils import debug
    ff = _build_model(workload, only_dp=True)
    cost = OpCostModel(ff.dmesh.spec)
    g = data_parallel_graph(
        ff.layers, ff.graph_inputs + getattr(ff, "const_inputs", []),
        [ff._output_tensor], ff.dmesh)
    est = GraphCostEvaluator(cost, ff.dmesh).graph_cost(g).peak_memory \
        / max(ff.dmesh.num_devices, 1)
    stats = debug.compiled_memory_stats(ff)
    compiled = (stats.get("argument_size_in_bytes", 0)
                + stats.get("output_size_in_bytes", 0)
                + stats.get("temp_size_in_bytes", 0))
    print("RESULT " + json.dumps({
        "workload": workload, "platform": jax.default_backend(),
        "estimate_bytes": int(est), "compiled": stats,
        "compiled_total_bytes": int(compiled),
        "ratio_est_over_compiled": round(est / max(compiled, 1), 3)}),
        flush=True)
    return 0


def _constrained_child(workload: str) -> int:
    from flexflow_tpu.utils import debug

    def build_wide_mlp(ff, _w, batch):
        # activation-dominated regime (batch >> hidden): per-layer DP
        # grad-sync (hidden^2 elems) is cheaper than TP activation
        # collectives (batch x hidden elems), so the cost-optimal winner
        # replicates ~9.4 MB of weights (x4 with grads+moments) on every
        # device — memory a binding --device-mem-mb can then reclaim by
        # forcing weight sharding
        from flexflow_tpu.models import build_mlp
        return build_mlp(ff, batch, in_dim=512,
                         hidden=(512,) * 8, num_classes=512)

    # slow interconnect makes replicated-weight DP the cost-optimal
    # winner, so a binding --device-mem-mb must CHANGE the strategy
    machine_file = os.path.join(REPO, "machine_configs",
                                "slow-fabric-8.json")

    def one(mem_mb: int):
        ff = _build_model(workload, only_dp=False, mem_mb=mem_mb,
                          batch=2048, builder=build_wide_mlp,
                          machine_file=machine_file)
        pred = getattr(ff, "_search_predicted", {}) or {}
        stats = debug.compiled_memory_stats(ff)
        per_dev_compiled = (stats.get("argument_size_in_bytes", 0)
                            + stats.get("output_size_in_bytes", 0)
                            + stats.get("temp_size_in_bytes", 0))
        return {"est_per_dev": int(pred.get("peak_mem_per_dev_bytes", 0)),
                "compiled_per_dev": int(per_dev_compiled),
                "compiled_args": stats.get("argument_size_in_bytes", 0),
                "searched_cost_s": pred.get("searched_cost_s")}

    free = one(0)
    budget_mb = max(1, int(free["est_per_dev"] * 0.6 / (1 << 20)))
    tight = one(budget_mb)
    print("RESULT " + json.dumps({
        "workload": workload, "unconstrained": free,
        "budget_mb": budget_mb, "constrained": tight,
        "fits_budget": tight["est_per_dev"] <= budget_mb * (1 << 20),
        "strategy_changed":
            tight["est_per_dev"] != free["est_per_dev"],
        # weight sharding shows up in the executable's argument size
        # (params + opt state); temps are activation/remat-dominated
        # and can move either way with resharding
        "compiled_args_shrank":
            tight["compiled_args"] < free["compiled_args"]}),
        flush=True)
    return 0


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--stage", default="")
    ap.add_argument("--workload", default="")
    ap.add_argument("--skip-constrained", action="store_true",
                    help="skip the CPU-only constrained-search stage "
                         "(the on-chip pipeline runs it separately — it "
                         "must not burn tunnel-window time)")
    ap.add_argument("--out", default=os.path.join(
        REPO, "bench_results", "r05_memory_validation.json"))
    a = ap.parse_args()
    if a.stage == "estimate":
        return _estimate_child(a.workload)
    if a.stage == "constrained":
        return _constrained_child(a.workload)

    out = {"estimate_vs_compiled": [], "constrained": None, "errors": {},
           "captured": time.strftime("%Y-%m-%d %H:%M:%S")}
    if a.skip_constrained and os.path.exists(a.out):
        # estimate-only refresh (tunnel window): keep the constrained
        # result captured by an earlier full run
        try:
            with open(a.out) as f:
                out["constrained"] = json.load(f).get("constrained")
        except Exception:  # noqa: BLE001
            pass

    def flush_out():
        """(Re)write after every stage — a pipeline stage timeout must
        never discard results already captured."""
        tmp = a.out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(out, f, indent=1)
        os.replace(tmp, a.out)

    def run(stage, workload, env=None, timeout=900):
        r = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--stage", stage,
             "--workload", workload],
            capture_output=True, text=True, timeout=timeout,
            env=dict(os.environ, **(env or {})), cwd=HERE)
        for line in r.stdout.splitlines():
            if line.startswith("RESULT "):
                return json.loads(line[len("RESULT "):])
        raise RuntimeError(f"rc={r.returncode}: " + (
            r.stderr.strip().splitlines() or ["?"])[-1][:200])

    for w in ESTIMATE_WORKLOADS:
        try:
            out["estimate_vs_compiled"].append(run("estimate", w))
        except Exception as e:  # noqa: BLE001 — continue the sweep
            out["errors"][f"estimate/{w}"] = str(e)[:300]
        flush_out()
        print(f"estimate/{w}: done", flush=True)
    if not a.skip_constrained:
        try:
            out["constrained"] = run(
                "constrained", "wide_mlp",
                env={"JAX_PLATFORMS": "cpu",
                     "XLA_FLAGS":
                         "--xla_force_host_platform_device_count=8"},
                timeout=1800)
        except Exception as e:  # noqa: BLE001
            out["errors"]["constrained"] = str(e)[:300]
        flush_out()
    print(f"wrote {a.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
