"""Inception-v3 (reference ``examples/cpp/InceptionV3``, osdi22ae
inception.sh: batch 64, budget 10). Reduced image size for CI."""
import numpy as np
from _common import run_example
from flexflow_tpu.models import build_inception_v3

HW = 75  # reference uses 299


def batch(cfg, rng):
    return {"input": rng.normal(size=(cfg.batch_size, 3, HW, HW))
            .astype(np.float32),
            "label": rng.integers(0, 10, size=(cfg.batch_size, 1))
            .astype(np.int32)}


if __name__ == "__main__":
    run_example("inception",
                lambda ff, cfg: build_inception_v3(ff, cfg.batch_size,
                                                   image_hw=HW),
                batch, steps=5)
