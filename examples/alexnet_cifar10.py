"""AlexNet / CIFAR-10 (reference ``bootcamp_demo/ff_alexnet_cifar10.py``,
BASELINE.json config 1). Synthetic CIFAR-shaped data."""
import numpy as np
from _common import run_example
from flexflow_tpu.models import build_alexnet_cifar10


def batch(cfg, rng):
    return {"input": rng.normal(size=(cfg.batch_size, 3, 32, 32))
            .astype(np.float32),
            "label": rng.integers(0, 10, size=(cfg.batch_size, 1))
            .astype(np.int32)}


if __name__ == "__main__":
    run_example("alexnet_cifar10",
                lambda ff, cfg: build_alexnet_cifar10(ff, cfg.batch_size),
                batch)
