"""A/B: DLRM with banked (device-subset) embedding placement vs
whole-mesh data parallelism, measured with real timed train steps.

Reference analog: the DLRM strategies placing embedding tables on
disjoint GPU subsets (``examples/cpp/DLRM/strategies/``). The banked
side shrinks the dense table-gradient all-reduce and the optimizer
update by the bank degree; this script measures that on the live mesh.

  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/dlrm_banked_ab.py --rows 200000 --steps 10 \
      --out bench_results/r04_dlrm_banked_ab.json
"""
from __future__ import annotations

import argparse
import json
import statistics
import time

import numpy as np


def _vocabs(rows: int, hetero: bool):
    if not hetero:
        return (rows,) * 4
    # heterogeneous tables averaging `rows` (the padded-bank case: the
    # reference's MachineView places NON-identical tables on subsets)
    return (rows // 2, rows * 3 // 4, rows * 5 // 4, rows * 3 // 2)


def build(banked: bool, rows: int, batch: int, hetero: bool = False):
    from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
    from flexflow_tpu.models import DLRMConfig, build_dlrm
    from flexflow_tpu.parallel.banks import (BankSpec, choose_bank_axes,
                                             find_bank_groups,
                                             group_is_padded)
    from flexflow_tpu.parallel.strategy import ShardingStrategy
    cfg = FFConfig()
    cfg.batch_size = batch
    cfg.only_data_parallel = True
    ff = FFModel(cfg)
    dcfg = DLRMConfig(embedding_size=_vocabs(rows, hetero))
    out = build_dlrm(ff, batch, dcfg)
    if not banked:
        ff.compile(SGDOptimizer(0.05), "sparse_categorical_crossentropy",
                   [], output_tensor=out)
        return ff, None
    ff.compile(SGDOptimizer(0.05), "sparse_categorical_crossentropy", [],
               output_tensor=out)
    st = ShardingStrategy.data_parallel(ff.layers, ff.graph_inputs,
                                        ff.dmesh)
    groups = find_bank_groups(ff.layers)
    assert groups, "no bank group found"
    padded = group_is_padded(groups[0])
    assert padded == hetero
    bank_axes, batch_axes = choose_bank_axes(ff.dmesh, len(groups[0]))
    bk = BankSpec([l.name for l in groups[0]], bank_axes,
                  batch_axes=batch_axes, param_name="__bank0__EMB",
                  padded=padded)
    st.banks = [bk]
    ff.compile(SGDOptimizer(0.05), "sparse_categorical_crossentropy", [],
               strategy=st, output_tensor=out)
    return ff, bk


def timed(ff, batch: int, steps: int, repeats: int):
    rng = np.random.default_rng(0)
    b = {}
    for t in ff.graph_inputs:
        if "sparse" in t.name:
            b[t.name] = rng.integers(0, 1000, size=t.shape).astype(np.int32)
        else:
            b[t.name] = rng.normal(size=t.shape).astype(np.float32)
    b["label"] = rng.integers(0, 2, size=(batch, 1)).astype(np.int32)
    step = ff.executor.make_train_step()
    bm = ff._run_train_step(step, b)
    float(np.asarray(bm["loss"]))     # compile + sync (D2H fetch)
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        for _ in range(steps):
            bm = ff._run_train_step(step, b)
        float(np.asarray(bm["loss"]))
        times.append((time.perf_counter() - t0) / steps)
    return (statistics.median(times),
            statistics.stdev(times) if len(times) > 1 else 0.0)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=200000)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--hetero", action="store_true",
                    help="heterogeneous vocab sizes (padded banks)")
    ap.add_argument("--out", default=None)
    a = ap.parse_args()
    import os
    import jax
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # the ambient TPU plugin ignores the env var; force it through
        # jax.config before anything touches devices (tests/conftest.py)
        jax.config.update("jax_platforms", "cpu")
    ff_dp, _ = build(False, a.rows, a.batch, a.hetero)
    t_dp, sd_dp = timed(ff_dp, a.batch, a.steps, a.repeats)
    del ff_dp
    ff_bk, bk = build(True, a.rows, a.batch, a.hetero)
    t_bk, sd_bk = timed(ff_bk, a.batch, a.steps, a.repeats)
    rec = {
        "workload": (f"dlrm_4x{a.rows}x64" if not a.hetero else
                     "dlrm_hetero_" + "x".join(
                         str(v) for v in _vocabs(a.rows, True))),
        "padded_banks": a.hetero,
        "platform": jax.default_backend(),
        "n_devices": len(jax.devices()),
        "bank_axes": list(bk.axes),
        "bank_degree": bk.bank_degree(ff_bk.dmesh),
        "whole_mesh_s_per_step": round(t_dp, 6),
        "whole_mesh_stdev": round(sd_dp, 6),
        "banked_s_per_step": round(t_bk, 6),
        "banked_stdev": round(sd_bk, 6),
        "speedup": round(t_dp / t_bk, 4),
        "steps": a.steps, "repeats": a.repeats,
    }
    print(json.dumps(rec))
    if a.out:
        with open(a.out, "w") as f:
            json.dump(rec, f, indent=1)


if __name__ == "__main__":
    main()
