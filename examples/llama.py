"""LLaMA-family causal LM (native build_llama: RMSNorm/SwiGLU/RoPE) on
synthetic next-token data. TPU-native addition beyond the reference's
model set."""
import numpy as np
from _common import run_example
from flexflow_tpu.models import LlamaConfig, build_llama

CFG = LlamaConfig(vocab_size=256, hidden_size=64, intermediate_size=128,
                  num_layers=2, num_heads=4, max_position=64)
SEQ = 32


def batch(cfg, rng):
    ids = rng.integers(0, CFG.vocab_size,
                       size=(cfg.batch_size, SEQ)).astype(np.int32)
    return {"input_ids": ids, "label": ids}


if __name__ == "__main__":
    run_example("llama",
                lambda ff, cfg: build_llama(ff, cfg.batch_size, SEQ, CFG),
                batch, loss="sparse_categorical_crossentropy",
                metrics=("accuracy",), steps=10)
