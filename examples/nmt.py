"""LSTM seq2seq NMT (reference legacy ``nmt/`` app: embed -> stacked
LSTM encoder/decoder -> attention -> vocab softmax) on a synthetic
copy task (translate = reproduce the source sequence)."""
import numpy as np
from _common import run_example
from flexflow_tpu.models import NMTConfig, build_nmt

CFG = NMTConfig(src_vocab=512, tgt_vocab=512, embed_dim=64,
                hidden_size=64, num_layers=2)
SRC_LEN = TGT_LEN = 16


def batch(cfg, rng):
    ids = rng.integers(1, CFG.src_vocab,
                       size=(cfg.batch_size, SRC_LEN)).astype(np.int32)
    # teacher forcing: decoder input is the gold shifted right (BOS=0)
    dec_in = np.concatenate(
        [np.zeros((cfg.batch_size, 1), np.int32), ids[:, :-1]], axis=1)
    return {"src_ids": ids, "tgt_ids": dec_in, "label": ids}


if __name__ == "__main__":
    run_example("nmt",
                lambda ff, cfg: build_nmt(ff, cfg.batch_size, SRC_LEN,
                                          TGT_LEN, CFG),
                batch, loss="sparse_categorical_crossentropy",
                metrics=("accuracy",), steps=10)
