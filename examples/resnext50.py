"""ResNeXt-50 (reference ``examples/cpp/resnext50``, osdi22ae
resnext-50.sh: batch 16, budget 20). Small image size default for CI."""
import numpy as np
from _common import run_example
from flexflow_tpu.models import build_resnext50

HW = 64  # reference uses 224; kept small so the example runs anywhere


def batch(cfg, rng):
    return {"input": rng.normal(size=(cfg.batch_size, 3, HW, HW))
            .astype(np.float32),
            "label": rng.integers(0, 10, size=(cfg.batch_size, 1))
            .astype(np.int32)}


if __name__ == "__main__":
    run_example("resnext50",
                lambda ff, cfg: build_resnext50(ff, cfg.batch_size,
                                                image_hw=HW),
                batch, steps=5)
