"""MoE classifier (reference ``examples/cpp/mixture_of_experts``):
top-k gate -> group_by -> per-expert dense -> aggregate."""
import numpy as np
from _common import run_example
from flexflow_tpu.models import MoeConfig, build_moe_mnist


CFG = MoeConfig()


def batch(cfg, rng):
    return {"input": rng.normal(size=(cfg.batch_size, CFG.in_dim))
            .astype(np.float32),
            "label": rng.integers(0, 10, size=(cfg.batch_size, 1))
            .astype(np.int32)}


if __name__ == "__main__":
    run_example("mixture_of_experts",
                lambda ff, cfg: build_moe_mnist(ff, cfg.batch_size, CFG),
                batch)
