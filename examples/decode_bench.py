"""Decode-path scaling benchmark: KV-cache vs full-re-forward
generation. The KV path's per-token cost must be independent of how
many tokens have been generated; the re-forward oracle is O(context)
per token. Writes one JSON record per (path, new_tokens) plus a
summary to bench_results/decode_scaling.json.

  python examples/decode_bench.py [--seq 256] [--layers 4]
"""
import argparse
import json
import os
import time

import numpy as np

import _common  # noqa: F401  — repo path + JAX_PLATFORMS=cpu honoring
from flexflow_tpu import FFConfig, FFModel, SGDOptimizer
from flexflow_tpu.models import GPTConfig, build_gpt2

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=256)
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--layers", type=int, default=4)
    ap.add_argument("--out", default=os.path.join(
        REPO, "bench_results", "decode_scaling.json"))
    a = ap.parse_args()

    g = GPTConfig(vocab_size=512, hidden_size=a.hidden,
                  num_layers=a.layers, num_heads=a.hidden // 32 or 2,
                  max_position=a.seq, dropout=0.0)
    cfg = FFConfig()
    cfg.batch_size = a.batch
    cfg.only_data_parallel = True
    ff = FFModel(cfg)
    out = build_gpt2(ff, a.batch, a.seq, g)
    ff.compile(SGDOptimizer(0.01), "sparse_categorical_crossentropy", [],
               output_tensor=out)
    rng = np.random.default_rng(0)
    plen = 8
    ids = np.zeros((a.batch, a.seq), np.int32)
    ids[:, :plen] = rng.integers(0, g.vocab_size, (a.batch, plen))

    def timed(kv, n_new):
        fn = lambda: np.asarray(ff.generate(  # noqa: E731
            ids, plen, n_new, kv_cache=kv))
        fn()                                   # compile
        t0 = time.perf_counter()
        reps = 3
        for _ in range(reps):
            fn()
        dt = (time.perf_counter() - t0) / reps
        return dt / n_new * 1e3                # ms/token

    lengths = [n for n in (16, 64, 192) if plen + n <= a.seq]
    if len(lengths) < 2:
        raise SystemExit(f"--seq {a.seq} too small: need room for at "
                         f"least two of 16/64/192 new tokens after the "
                         f"{plen}-token prompt")
    results = []
    for kv in (True, False):
        per_tok = {}
        for n in lengths:
            per_tok[n] = round(timed(kv, n), 3)
            rec = {"path": "kv" if kv else "reforward",
                   "new_tokens": n, "ms_per_token": per_tok[n]}
            print(json.dumps(rec), flush=True)
        results.append({"path": "kv" if kv else "reforward",
                        "ms_per_token_by_len": per_tok})
    kv_tok = results[0]["ms_per_token_by_len"]
    rf_tok = results[1]["ms_per_token_by_len"]
    lo, hi = lengths[0], lengths[-1]

    def incr(tok):
        # INCREMENTAL per-token cost between the two lengths: strips
        # the fixed prefill/dispatch share that the amortized numbers
        # spread over more tokens
        return (tok[hi] * hi - tok[lo] * lo) / (hi - lo)

    doc = {
        "_comment": "KV-cache decode per-token cost vs generated "
                    "length (VERDICT r2 item 3: must be independent of "
                    "length; the re-forward oracle grows with context). "
                    "ms_per_token_by_len amortizes prefill; the "
                    "incremental_* fields are the marginal cost of one "
                    "more token and carry the scaling claim.",
        "model": f"gpt2 h{a.hidden} L{a.layers} seq{a.seq} b{a.batch}",
        "platform_env": os.environ.get("JAX_PLATFORMS", "default"),
        "results": results,
        "incremental_ms_per_token_kv": round(incr(kv_tok), 3),
        "incremental_ms_per_token_reforward": round(incr(rf_tok), 3),
        "kv_speedup_incremental": round(incr(rf_tok) / incr(kv_tok), 2),
        "kv_speedup_at_longest": round(rf_tok[hi] / kv_tok[hi], 2),
    }
    os.makedirs(os.path.dirname(a.out), exist_ok=True)
    with open(a.out, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"wrote {a.out}", flush=True)
    print(f"incremental ms/token: kv "
          f"{doc['incremental_ms_per_token_kv']} vs re-forward "
          f"{doc['incremental_ms_per_token_reforward']} "
          f"({doc['kv_speedup_incremental']}x)", flush=True)


if __name__ == "__main__":
    main()
