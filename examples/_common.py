"""Shared runner for the example suite.

The reference's examples double as its integration suite (SURVEY.md §4:
``tests/multi_gpu_tests.sh`` runs every example with accuracy callbacks);
these examples follow the same pattern: build a model from the zoo, train
on synthetic (or downloaded) data, print throughput, and — with ``--ab`` —
run the searched-strategy vs data-parallel A/B the OSDI'22 artifact scripts
perform (``scripts/osdi22ae/*.sh``).
"""
from __future__ import annotations

import os
import sys
import time
from typing import Callable, Dict, Optional

import numpy as np

# examples are runnable standalone (cwd=examples/) without pip-installing
# the package: put the repo root on sys.path ahead of the import below
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:
    sys.path.insert(0, _REPO)

# honor JAX_PLATFORMS=cpu even when a TPU platform plugin is ambient
# (the plugin ignores the env var; jax.config after import does not)
if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    import jax
    jax.config.update("jax_platforms", "cpu")

from flexflow_tpu import FFConfig, FFModel, SGDOptimizer


def run_example(name: str, build: Callable[[FFModel, FFConfig], object],
                make_batch: Callable[[FFConfig, np.random.Generator], Dict],
                loss: str = "sparse_categorical_crossentropy",
                metrics=("accuracy",), steps: int = 20,
                argv: Optional[list] = None):
    """Build + train `steps` iterations; honors reference CLI flags.

    With --ab: times data-parallel THEN the searched strategy on the same
    model/batch and reports the ratio (the osdi22ae A/B)."""
    import sys
    argv = list(sys.argv[1:] if argv is None else argv)
    ab = "--ab" in argv
    if ab:
        argv.remove("--ab")
    def _take_int_flag(flag: str, default: int) -> int:
        """Pop `--flag N` or `--flag=N` from argv; clear error if N is
        missing/non-numeric (FFConfig would reject the leftover flag)."""
        for i, a in enumerate(argv):
            if a == flag or a.startswith(flag + "="):
                if "=" in a:
                    raw, end = a.split("=", 1)[1], i + 1
                else:
                    if i + 1 >= len(argv):
                        raise SystemExit(f"{flag} requires a value")
                    raw, end = argv[i + 1], i + 2
                try:
                    val = int(raw)
                except ValueError:
                    raise SystemExit(f"{flag} expects an int, got {raw!r}")
                del argv[i:end]
                return val
        return default

    repeats = max(1, _take_int_flag("--repeats", 1))
    steps = max(steps, _take_int_flag("--min-steps", 0))
    cfg = FFConfig.parse_args(argv)

    def timed(only_dp: bool) -> float:
        c = FFConfig.parse_args(argv)
        c.only_data_parallel = only_dp or cfg.only_data_parallel
        ff = FFModel(c)
        out = build(ff, c)
        ff.compile(SGDOptimizer(c.learning_rate), loss, list(metrics),
                   output_tensor=out if out is not None else None)
        rng = np.random.default_rng(0)
        b = make_batch(c, rng)
        step = ff.executor.make_train_step()
        bm = ff._run_train_step(step, b)     # compile + warmup
        float(np.asarray(bm["loss"]))
        # --repeats N times the steady-state loop N times on the same
        # compiled step and reports mean +/- stddev, so A/B ratios carry
        # error bars instead of a single noisy sample
        runs = []
        for _ in range(repeats):
            t0 = time.perf_counter()
            for _ in range(steps):
                bm = ff._run_train_step(step, b)
            loss_v = float(np.asarray(bm["loss"]))  # D2H sync
            dt = time.perf_counter() - t0
            runs.append(c.batch_size * steps / dt)
        sps = float(np.mean(runs))
        std = float(np.std(runs, ddof=1)) if len(runs) > 1 else 0.0
        mode = "data-parallel" if c.only_data_parallel else "searched"
        # fixed-point, never scientific: osdi22ae/run_all.py parses this
        print(f"[{name}] {mode}: {sps:.3f} samples/s "
              f"(std {std:.3f}, n={repeats}, loss {loss_v:.4f}, "
              f"{steps} steps in {dt:.2f}s)")
        pred = getattr(ff, "_search_predicted", None)
        if pred and not c.only_data_parallel:
            ratio = pred["dp_cost_s"] / max(pred["searched_cost_s"], 1e-12)
            print(f"[{name}] predicted searched-vs-dp: {ratio:.4f}x")
        guard = getattr(ff, "_floor_guard_record", None)
        if guard and not c.only_data_parallel:
            print(f"[{name}] floor-guard adopted: {guard['adopted']}")
        assert np.isfinite(loss_v)
        return sps

    if ab:
        dp = timed(only_dp=True)
        searched = timed(only_dp=False)
        print(f"[{name}] searched vs data-parallel: {searched / dp:.2f}x")
    else:
        timed(only_dp=cfg.only_data_parallel)
