#!/bin/bash
# Round-5 on-chip capture pipeline (invoked by r05_capture_daemon.sh the
# moment the tunnel answers). Stages in VERDICT-r4 priority order; a
# failing stage does not stop later ones. Every stage has a hard
# timeout — recovery windows have historically been short (~40 min), so
# the cheap, highest-value stages go first.
cd /root/repo || exit 1
export PYTHONPATH=/root/repo:/root/.axon_site
export JAX_PLATFORMS=axon
R=/root/repo/bench_results

echo "[pipeline $(date +%H:%M:%S)] stage 1: kernel validation"
timeout 1800 python examples/tpu_validate_kernels.py \
  > "$R/r05_kernel_validation.log" 2>&1
echo "[pipeline $(date +%H:%M:%S)] validation rc=$?"

echo "[pipeline $(date +%H:%M:%S)] stage 2: calibrate + fidelity"
timeout 2400 python examples/tpu_fidelity.py \
  > "$R/r05_fidelity.log" 2>&1
echo "[pipeline $(date +%H:%M:%S)] fidelity rc=$?"

echo "[pipeline $(date +%H:%M:%S)] stage 3: MFU sweep"
timeout 3600 python examples/tpu_profile_bert.py --steps 20 \
  --out "$R/r05_profile.json" \
  > "$R/r05_profile.log" 2>&1
echo "[pipeline $(date +%H:%M:%S)] profile rc=$?"

echo "[pipeline $(date +%H:%M:%S)] stage 4: bench.py"
BENCH_DEADLINE_S=2400 timeout 2600 python bench.py \
  > "$R/r05_onchip_bench.log" 2>&1
echo "[pipeline $(date +%H:%M:%S)] bench rc=$?"
tail -1 "$R/r05_onchip_bench.log" > "$R/r05_onchip.json" 2>/dev/null

echo "[pipeline $(date +%H:%M:%S)] stage 5: memory validation (estimate only; the CPU-only constrained stage runs outside tunnel windows)"
timeout 1200 python examples/tpu_memory_validation.py --skip-constrained \
  > "$R/r05_memory_validation.log" 2>&1
echo "[pipeline $(date +%H:%M:%S)] memory rc=$?"
