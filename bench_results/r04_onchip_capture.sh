#!/bin/bash
# Round-4 opportunistic on-chip capture daemon.
# Probes the axon tunnel every ~8 min; the moment it answers, runs the
# FULL capture pipeline immediately (the r3 wedge showed recovery
# windows can be short):
#   1. kernel validation (flash fwd/bwd + dropout vs goldens, compiled)
#   2. BERT MFU sweep (bf16-activations x flash on/off)
#   3. bench.py searched-vs-DP A/B
# Artifacts land in bench_results/; one pipeline stage failing does not
# stop the later ones. Exits after one full pass (rerun for more).
cd /root/repo || exit 1
export PYTHONPATH=/root/repo:/root/.axon_site
LOG=/root/repo/bench_results/r04_capture_daemon.log
echo "[$(date +%H:%M:%S)] daemon start" >> "$LOG"
for i in $(seq 1 200); do
  JAX_PLATFORMS=axon timeout 180 python -c "
import jax, numpy as np
x = jax.numpy.ones((256,256))
print('probe-ok', float(np.asarray((x@x).sum())))
" >> "$LOG" 2>&1
  if [ $? -ne 0 ]; then
    echo "[$(date +%H:%M:%S)] probe $i down" >> "$LOG"
    sleep 420
    continue
  fi
  echo "[$(date +%H:%M:%S)] TPU ALIVE — capturing" >> "$LOG"
  date +%s > /root/repo/bench_results/tpu_alive.flag

  timeout 2400 python examples/tpu_validate_kernels.py \
    > bench_results/r04_kernel_validation_full.log 2>&1
  echo "[$(date +%H:%M:%S)] validation rc=$?" >> "$LOG"

  timeout 3600 python examples/tpu_profile_bert.py --steps 20 \
    > bench_results/r04_profile.log 2>&1
  echo "[$(date +%H:%M:%S)] profile rc=$?" >> "$LOG"

  BENCH_DEADLINE_S=2400 timeout 2600 python bench.py \
    > bench_results/r04_onchip_bench.log 2>&1
  echo "[$(date +%H:%M:%S)] bench rc=$?" >> "$LOG"
  tail -1 bench_results/r04_onchip_bench.log \
    > bench_results/r04_onchip.json 2>/dev/null
  echo "[$(date +%H:%M:%S)] capture pass complete" >> "$LOG"
  exit 0
done
