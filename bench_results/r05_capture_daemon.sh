#!/bin/bash
# Round-5 opportunistic on-chip capture daemon.
# Probes the axon tunnel every ~7 min; the moment it answers, runs
# bench_results/r05_pipeline.sh (kept in a separate file so the
# pipeline can be extended while this loop is already running — bash
# reads a script as it executes it, so editing THIS file mid-run is
# unsafe, but editing the pipeline file is fine).
# After a successful full pass it keeps probing and re-runs the
# pipeline at most once more if >2h have passed (fresher artifacts win).
cd /root/repo || exit 1
export PYTHONPATH=/root/repo:/root/.axon_site
LOG=/root/repo/bench_results/r05_capture_daemon.log
echo "[$(date +%H:%M:%S)] daemon start" >> "$LOG"
PASSES=0
LAST_PASS=0
for i in $(seq 1 400); do
  JAX_PLATFORMS=axon timeout 180 python -c "
import jax, numpy as np
x = jax.numpy.ones((256,256))
print('probe-ok', float(np.asarray((x@x).sum())))
" >> "$LOG" 2>&1
  if [ $? -ne 0 ]; then
    echo "[$(date +%H:%M:%S)] probe $i down" >> "$LOG"
    sleep 380
    continue
  fi
  NOW=$(date +%s)
  if [ $PASSES -ge 2 ]; then
    echo "[$(date +%H:%M:%S)] probe $i ok (2 passes done, idle)" >> "$LOG"
    sleep 1800
    continue
  fi
  if [ $PASSES -ge 1 ] && [ $((NOW - LAST_PASS)) -lt 7200 ]; then
    echo "[$(date +%H:%M:%S)] probe $i ok (pass done, waiting)" >> "$LOG"
    sleep 900
    continue
  fi
  echo "[$(date +%H:%M:%S)] TPU ALIVE — running pipeline (pass $PASSES)" >> "$LOG"
  date +%s > /root/repo/bench_results/tpu_alive.flag
  bash /root/repo/bench_results/r05_pipeline.sh >> "$LOG" 2>&1
  PASSES=$((PASSES+1))
  LAST_PASS=$(date +%s)
  echo "[$(date +%H:%M:%S)] pipeline pass $PASSES complete" >> "$LOG"
done
