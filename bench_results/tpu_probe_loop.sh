#!/bin/bash
# Probe the axon TPU tunnel every ~8 min; on success, write a flag file and exit.
# Used during round builds so on-chip capture can start the moment the tunnel recovers.
FLAG=/root/repo/bench_results/tpu_alive.flag
LOG=/root/repo/bench_results/tpu_probe_loop.log
rm -f "$FLAG"
for i in $(seq 1 100); do
  echo "[$(date +%H:%M:%S)] probe attempt $i" >> "$LOG"
  PYTHONPATH=/root/repo:/root/.axon_site JAX_PLATFORMS=axon timeout 180 python -c "
import jax, numpy as np
x = jax.numpy.ones((256,256))
print('probe-ok', float(np.asarray((x@x).sum())))
" >> "$LOG" 2>&1
  if [ $? -eq 0 ]; then
    echo "[$(date +%H:%M:%S)] TPU ALIVE" >> "$LOG"
    date +%s > "$FLAG"
    exit 0
  fi
  sleep 420
done
exit 1
